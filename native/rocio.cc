// rocio — native host-side data layer for the roc_tpu framework.
//
// TPU-native rebuild of the reference's C++/CUDA host data path:
//   * .lux binary graph reader        (reference gnn.cc:756-801,
//                                      load_task.cu:229-243)
//   * CSV feature parser              (reference load_task.cu:41-73)
//   * Train/Val/Test/None mask parser (reference load_task.cu:169-183)
//   * edge-balanced greedy partitioner (reference gnn.cc:806-829)
//   * self-edge insertion             (the offline .add_self_edge.lux
//                                      preprocessing, gnn.cc:756)
//
// Exposed as a C ABI consumed from Python via ctypes
// (roc_tpu/native.py); all buffers are caller-allocated numpy arrays.
// Error returns are negative; 0 is success.

#include <cerrno>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <algorithm>
#include <vector>

namespace {

constexpr int kOk = 0;
constexpr int kErrOpen = -1;
constexpr int kErrRead = -2;
constexpr int kErrFormat = -3;
constexpr int kErrValue = -4;

struct FileCloser {
  FILE* f;
  ~FileCloser() {
    if (f) fclose(f);
  }
};

}  // namespace

extern "C" {

// Bumped on every C-ABI signature change; roc_tpu/native.py refuses a
// library whose version does not match (a stale/pinned .so called with
// new argtypes would read a pointer as an int — SIGSEGV or garbage).
// v2: sub_w parameter inserted into roc_sectioned_counts/_fill.
// v4: num_cols parameter inserted into roc_block_counts/_fill (the
//     distributed block-dense planner tiles a RECTANGULAR space:
//     local dst rows x gathered source coordinates).
// v5: roc_lpa_iterate added (label-propagation vertex ordering).
int roc_abi_version(void) { return 5; }

// ---------------------------------------------------------------------------
// .lux binary format: u32 num_nodes, u64 num_edges, num_nodes x u64
// inclusive-end row offsets, num_edges x u32 source ids (dst-sorted CSR).
// ---------------------------------------------------------------------------

int roc_lux_header(const char* path, uint32_t* num_nodes,
                   uint64_t* num_edges) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrOpen;
  FileCloser closer{f};
  if (fread(num_nodes, sizeof(uint32_t), 1, f) != 1) return kErrRead;
  if (fread(num_edges, sizeof(uint64_t), 1, f) != 1) return kErrRead;
  return kOk;
}

// row_ptr: int64 [num_nodes + 1] (exclusive-start, row_ptr[0] = 0);
// col_idx: int32 [num_edges].  Validates monotone offsets and final
// offset == num_edges (the reference asserts the same, gnn.cc:798-800).
int roc_lux_read(const char* path, int64_t num_nodes, int64_t num_edges,
                 int64_t* row_ptr, int32_t* col_idx) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrOpen;
  FileCloser closer{f};
  if (fseek(f, sizeof(uint32_t) + sizeof(uint64_t), SEEK_SET) != 0)
    return kErrRead;

  row_ptr[0] = 0;
  constexpr int64_t kChunk = 1 << 20;
  void* heap = malloc(kChunk * sizeof(uint64_t));
  if (!heap) return kErrRead;
  {
    uint64_t* buf = (uint64_t*)heap;
    int64_t done = 0;
    int64_t prev = 0;
    while (done < num_nodes) {
      int64_t n = num_nodes - done < kChunk ? num_nodes - done : kChunk;
      if ((int64_t)fread(buf, sizeof(uint64_t), n, f) != n) {
        free(heap);
        return kErrRead;
      }
      for (int64_t i = 0; i < n; ++i) {
        int64_t v = (int64_t)buf[i];
        if (v < prev) {
          free(heap);
          return kErrFormat;  // monotonicity
        }
        row_ptr[done + i + 1] = v;
        prev = v;
      }
      done += n;
    }
    if (prev != num_edges) {
      free(heap);
      return kErrFormat;
    }
  }
  {
    uint32_t* buf = (uint32_t*)heap;
    int64_t done = 0;
    while (done < num_edges) {
      int64_t n = num_edges - done < 2 * kChunk ? num_edges - done
                                                : 2 * kChunk;
      if ((int64_t)fread(buf, sizeof(uint32_t), n, f) != n) {
        free(heap);
        return kErrRead;
      }
      for (int64_t i = 0; i < n; ++i) {
        if (buf[i] >= (uint64_t)num_nodes) {
          free(heap);
          return kErrValue;
        }
        col_idx[done + i] = (int32_t)buf[i];
      }
      done += n;
    }
  }
  free(heap);
  return kOk;
}

int roc_lux_write(const char* path, int64_t num_nodes, int64_t num_edges,
                  const int64_t* row_ptr, const int32_t* col_idx) {
  FILE* f = fopen(path, "wb");
  if (!f) return kErrOpen;
  FileCloser closer{f};
  uint32_t v32 = (uint32_t)num_nodes;
  uint64_t e64 = (uint64_t)num_edges;
  if (fwrite(&v32, sizeof(v32), 1, f) != 1) return kErrRead;
  if (fwrite(&e64, sizeof(e64), 1, f) != 1) return kErrRead;
  for (int64_t v = 1; v <= num_nodes; ++v) {
    uint64_t off = (uint64_t)row_ptr[v];
    if (fwrite(&off, sizeof(off), 1, f) != 1) return kErrRead;
  }
  for (int64_t e = 0; e < num_edges; ++e) {
    uint32_t s = (uint32_t)col_idx[e];
    if (fwrite(&s, sizeof(s), 1, f) != 1) return kErrRead;
  }
  return kOk;
}

// ---------------------------------------------------------------------------
// CSV feature parser: `rows` lines of `cols` comma-separated floats.
// Orders of magnitude faster than np.loadtxt on Reddit-scale matrices.
// ---------------------------------------------------------------------------

namespace {
inline bool is_csv_sep(char c) {
  return c == ',' || c == '\n' || c == '\r' || c == ' ' || c == '\t';
}

// Locale-independent float parse of [tok, end).  Prefers
// std::from_chars (GCC 11+ ships the float overload); older libstdc++
// falls back to strtof with temporary NUL termination — *end is
// writable in both call sites (a separator byte, or the sentinel slot
// past the chunk buffer).  Returns false on malformed input.
inline bool parse_float_tok(char* tok, char* end, float* v) {
  if (*tok == '+') ++tok;  // from_chars rejects the leading '+'
                           // that strtof/np.loadtxt accept
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  auto res = std::from_chars(tok, end, *v);
  return res.ec == std::errc{} && res.ptr == end;
#else
  char saved = *end;
  *end = '\0';
  char* stop = nullptr;
  errno = 0;
  *v = strtof(tok, &stop);
  *end = saved;
  return stop == end && errno != ERANGE;
#endif
}
}  // namespace

int roc_load_features_csv(const char* path, float* out, int64_t rows,
                          int64_t cols) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrOpen;
  FileCloser closer{f};
  // Fixed-size chunked parse (constant memory at any file size); a
  // token split across a chunk boundary is carried to the front of the
  // next chunk.  std::from_chars is locale-independent — strtof under
  // a non-C LC_NUMERIC would reject valid '.'-separated files.
  constexpr size_t kBuf = size_t{1} << 22;  // 4 MiB
  char* buf = (char*)malloc(kBuf + 1);
  if (!buf) return kErrRead;
  const int64_t total = rows * cols;
  int64_t i = 0;
  size_t carry = 0;
  int rc = kOk;
  for (;;) {
    size_t got = fread(buf + carry, 1, kBuf - carry, f);
    if (got == 0 && ferror(f)) {
      // a mid-file I/O failure is a read error, not a shape mismatch
      free(buf);
      return kErrRead;
    }
    size_t len = carry + got;
    const bool eof = got == 0;
    carry = 0;
    char* p = buf;
    char* const lim = buf + len;
    while (p < lim) {
      if (is_csv_sep(*p)) {
        ++p;
        continue;
      }
      char* tok = p;
      while (p < lim && !is_csv_sep(*p)) ++p;
      if (p == lim && !eof) {
        // token may continue in the next chunk
        carry = (size_t)(lim - tok);
        if (carry == kBuf) {
          rc = kErrFormat;  // single token larger than the buffer
        } else {
          memmove(buf, tok, carry);
        }
        break;
      }
      float v;
      if (!parse_float_tok(tok, p, &v)) {
        rc = kErrFormat;
        break;
      }
      if (i >= total) {
        // file holds more values than the declared shape
        rc = kErrFormat;
        break;
      }
      out[i++] = v;
    }
    if (rc != kOk || eof) break;
  }
  free(buf);
  // Exact-count check: a wrong `cols` mis-aligns every row, so both
  // under- and over-full files are format errors (the numpy fallback's
  // reshape raises in the same cases).
  return (rc == kOk && i == total) ? kOk : (rc != kOk ? rc : kErrFormat);
}

// Partition-local CSV read: skip `row_lo` newline-terminated lines,
// then parse (row_hi - row_lo) * cols floats.  The skip scans chunks
// counting '\n' without tokenizing — the reference loader's
// skip-to-rowLeft behavior (load_task.cu:41-51) for text features.
int roc_load_features_csv_rows(const char* path, float* out,
                               int64_t row_lo, int64_t row_hi,
                               int64_t cols) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrOpen;
  FileCloser closer{f};
  constexpr size_t kBuf = size_t{1} << 22;
  char* buf = (char*)malloc(kBuf + 1);
  if (!buf) return kErrRead;
  // Phase 1: skip row_lo lines.
  int64_t lines = 0;
  size_t resume = 0;  // unconsumed bytes at buf start for phase 2
  size_t len = 0;
  char* p = nullptr;
  while (lines < row_lo) {
    len = fread(buf, 1, kBuf, f);
    if (len == 0) {
      free(buf);
      return ferror(f) ? kErrRead : kErrFormat;  // fewer lines than rows
    }
    p = buf;
    char* const lim = buf + len;
    while (p < lim && lines < row_lo) {
      char* nl = (char*)memchr(p, '\n', (size_t)(lim - p));
      if (!nl) {
        p = lim;
        break;
      }
      ++lines;
      p = nl + 1;
    }
    if (lines == row_lo) {
      resume = (size_t)(buf + len - p);
      memmove(buf, p, resume);
      break;
    }
  }
  // Phase 2: parse exactly (row_hi - row_lo) * cols values, reusing the
  // chunked tokenizer with the carried tail.
  const int64_t total = (row_hi - row_lo) * cols;
  int64_t i = 0;
  size_t carry = resume;
  int rc = kOk;
  while (i < total) {
    size_t got = fread(buf + carry, 1, kBuf - carry, f);
    if (got == 0 && ferror(f)) {
      free(buf);
      return kErrRead;
    }
    size_t n = carry + got;
    const bool eof = got == 0;
    carry = 0;
    char* q = buf;
    char* const lim = buf + n;
    while (q < lim && i < total) {
      if (is_csv_sep(*q)) {
        ++q;
        continue;
      }
      char* tok = q;
      while (q < lim && !is_csv_sep(*q)) ++q;
      if (q == lim && !eof) {
        carry = (size_t)(lim - tok);
        if (carry == kBuf) {
          rc = kErrFormat;
        } else {
          memmove(buf, tok, carry);
        }
        break;
      }
      float v;
      if (!parse_float_tok(tok, q, &v)) {
        rc = kErrFormat;
        break;
      }
      out[i++] = v;
    }
    if (rc != kOk || (eof && i < total)) break;
  }
  free(buf);
  if (rc != kOk) return rc;
  return i == total ? kOk : kErrFormat;
}

// ---------------------------------------------------------------------------
// Mask parser: one of "Train"/"Val"/"Test"/"None" per line -> int32
// {1, 2, 3, 0} — the framework's MASK_* encoding (roc_tpu/core/graph.py
// MASK_TRAIN/VAL/TEST/NONE and its numpy fallback).  Note the reference
// enum MaskType orders TRAIN=0/VAL=1/TEST=2/NONE=3 (gnn.h:98-103); only
// the on-disk tokens are shared, not the integer values.  Tokens are
// compared whole, like the numpy fallback — no prefix acceptance.
// ---------------------------------------------------------------------------

int roc_load_mask(const char* path, int32_t* out, int64_t n) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrOpen;
  FileCloser closer{f};
  char line[64];
  for (int64_t v = 0; v < n; ++v) {
    if (!fgets(line, sizeof(line), f)) return kErrRead;
    // strip surrounding whitespace like the fallback's str.strip()
    char* tok = line;
    while (*tok == ' ' || *tok == '\t') ++tok;
    size_t end = strlen(tok);
    while (end > 0 && (tok[end - 1] == '\n' || tok[end - 1] == '\r' ||
                       tok[end - 1] == ' ' || tok[end - 1] == '\t'))
      --end;
    tok[end] = '\0';
    if (strcmp(tok, "Train") == 0) {
      out[v] = 1;
    } else if (strcmp(tok, "Val") == 0) {
      out[v] = 2;
    } else if (strcmp(tok, "Test") == 0) {
      out[v] = 3;
    } else if (strcmp(tok, "None") == 0) {
      out[v] = 0;
    } else {
      return kErrFormat;
    }
  }
  return kOk;
}

// ---------------------------------------------------------------------------
// Edge-balanced greedy partitioner (reference gnn.cc:806-829): walk
// vertices accumulating in-degree; close a range when the running count
// exceeds cap = ceil(E / num_parts).  bounds: int64 [num_parts, 2]
// inclusive [left, right]; empty tail ranges get left > right.
// ---------------------------------------------------------------------------

int roc_edge_balanced_bounds(const int64_t* row_ptr, int64_t num_nodes,
                             int64_t num_parts, int64_t* bounds) {
  if (num_parts <= 0) return kErrValue;
  int64_t num_edges = row_ptr[num_nodes];
  int64_t cap = (num_edges + num_parts - 1) / num_parts;
  int64_t part = 0;
  int64_t left = 0;
  int64_t cnt = 0;
  for (int64_t v = 0; v < num_nodes; ++v) {
    cnt += row_ptr[v + 1] - row_ptr[v];
    if (cnt > cap && part < num_parts - 1) {
      bounds[2 * part] = left;
      bounds[2 * part + 1] = v;
      ++part;
      left = v + 1;
      cnt = 0;
    }
  }
  bounds[2 * part] = left;
  bounds[2 * part + 1] = num_nodes - 1;
  ++part;
  for (; part < num_parts; ++part) {
    bounds[2 * part] = num_nodes;      // empty tail range
    bounds[2 * part + 1] = num_nodes - 1;
  }
  return kOk;
}

// ---------------------------------------------------------------------------
// Self-edge insertion (the offline `.add_self_edge.lux` conversion the
// reference assumes, gnn.cc:756).  Two-phase: count, then fill.
// new_row_ptr: int64 [V+1]; new_col_idx: int32 [E + missing].
// Returns the number of inserted edges (>= 0) or a negative error.
// ---------------------------------------------------------------------------

int64_t roc_add_self_edges(const int64_t* row_ptr, const int32_t* col_idx,
                           int64_t num_nodes, int64_t* new_row_ptr,
                           int32_t* new_col_idx, int64_t new_capacity) {
  // Pass 1: which rows already have a self edge?
  int64_t missing = 0;
  for (int64_t v = 0; v < num_nodes; ++v) {
    bool has = false;
    for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; ++e) {
      if (col_idx[e] == v) {
        has = true;
        break;
      }
    }
    // stash per-row flag in new_row_ptr temporarily
    new_row_ptr[v + 1] = has ? 0 : 1;
    missing += has ? 0 : 1;
  }
  int64_t new_edges = row_ptr[num_nodes] + missing;
  if (new_edges > new_capacity) return kErrValue;
  // Pass 2: fill, keeping per-row edges contiguous (dst-major order).
  int64_t out = 0;
  new_row_ptr[0] = 0;
  for (int64_t v = 0; v < num_nodes; ++v) {
    bool insert = new_row_ptr[v + 1] != 0;
    for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; ++e)
      new_col_idx[out++] = col_idx[e];
    if (insert) new_col_idx[out++] = (int32_t)v;
    new_row_ptr[v + 1] = out;
  }
  return missing;
}

// ---------------------------------------------------------------------------
// ELL bucket shape computation: per-row power-of-two width bucket
// (floored at min_width).  Returns per-row widths so Python can
// allocate the stacked arrays without a per-row Python loop.
// ---------------------------------------------------------------------------

int roc_ell_widths(const int64_t* row_ptr, int64_t num_rows,
                   int32_t min_width, int32_t* widths) {
  for (int64_t v = 0; v < num_rows; ++v) {
    int64_t d = row_ptr[v + 1] - row_ptr[v];
    int32_t w = min_width;
    while (w < d) w *= 2;
    widths[v] = d == 0 ? 0 : w;
  }
  return kOk;
}

// ---------------------------------------------------------------------------
// Sectioned fast-gather layout prep (core/ell.py SectionedEll): the
// O(E) host pass that splits each dst row's neighbor list by source
// section and emits width-8 sub-rows.  Two passes behind a C ABI with
// caller-allocated buffers, like everything else in this file:
// counts (so Python can compute the uniform chunk plan and allocate)
// then fill.  Both walk the dst-major CSR once — O(E + V * n_sec).
// ---------------------------------------------------------------------------

int roc_sectioned_counts(const int64_t* row_ptr, const int32_t* col,
                         int64_t num_rows, int64_t section_rows,
                         int64_t n_sec, int64_t sub_w,
                         int64_t* counts) {
  if (sub_w <= 0) return kErrValue;
  std::vector<int64_t> local(static_cast<size_t>(n_sec));
  for (int64_t s = 0; s < n_sec; ++s) counts[s] = 0;
  for (int64_t v = 0; v < num_rows; ++v) {
    std::fill(local.begin(), local.end(), 0);
    for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; ++e) {
      int64_t s = col[e] / section_rows;
      if (col[e] < 0 || s >= n_sec) return kErrValue;  // out of range
      local[static_cast<size_t>(s)] += 1;
    }
    for (int64_t s = 0; s < n_sec; ++s) {
      counts[s] += (local[static_cast<size_t>(s)] + sub_w - 1) / sub_w;
    }
  }
  return kOk;
}

// sec_sizes[s]: the section's row count == its local dummy id.
// slots[s]: allocated sub-rows per section (chunk plan * seg_rows);
// must be >= the counts pass's result or kErrValue is returned.
// idx_flat: [sum(slots) * sub_w] int32; sub_dst_flat: [sum(slots)] int32.
// Sub-rows are emitted in ascending dst order per section (matching
// the numpy builder exactly); leftover slots become padding sub-rows
// (idx = section dummy, sub_dst = num_rows).
int roc_sectioned_fill(const int64_t* row_ptr, const int32_t* col,
                       int64_t num_rows, int64_t section_rows,
                       int64_t n_sec, int64_t sub_w,
                       const int64_t* sec_sizes,
                       const int64_t* slots, int32_t* idx_flat,
                       int32_t* sub_dst_flat) {
  if (sub_w <= 0) return kErrValue;
  std::vector<int64_t> cursor(static_cast<size_t>(n_sec));
  std::vector<int64_t> limit(static_cast<size_t>(n_sec));
  int64_t off = 0;
  for (int64_t s = 0; s < n_sec; ++s) {
    cursor[static_cast<size_t>(s)] = off;
    off += slots[s];
    limit[static_cast<size_t>(s)] = off;
  }
  std::vector<std::vector<int32_t>> buf(static_cast<size_t>(n_sec));
  for (int64_t v = 0; v < num_rows; ++v) {
    for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; ++e) {
      int64_t s = col[e] / section_rows;
      if (col[e] < 0 || s >= n_sec) return kErrValue;  // out of range
      buf[static_cast<size_t>(s)].push_back(
          static_cast<int32_t>(col[e] - s * section_rows));
    }
    for (int64_t s = 0; s < n_sec; ++s) {
      std::vector<int32_t>& b = buf[static_cast<size_t>(s)];
      if (b.empty()) continue;
      int64_t nsub =
          (static_cast<int64_t>(b.size()) + sub_w - 1) / sub_w;
      if (cursor[static_cast<size_t>(s)] + nsub >
          limit[static_cast<size_t>(s)]) {
        return kErrValue;  // plan smaller than the counts pass said
      }
      int64_t base = cursor[static_cast<size_t>(s)] * sub_w;
      for (int64_t k = 0; k < nsub * sub_w; ++k) {
        idx_flat[base + k] =
            k < static_cast<int64_t>(b.size())
                ? b[static_cast<size_t>(k)]
                : static_cast<int32_t>(sec_sizes[s]);
      }
      for (int64_t j = 0; j < nsub; ++j) {
        sub_dst_flat[cursor[static_cast<size_t>(s)] + j] =
            static_cast<int32_t>(v);
      }
      cursor[static_cast<size_t>(s)] += nsub;
      b.clear();
    }
  }
  for (int64_t s = 0; s < n_sec; ++s) {
    for (int64_t slot = cursor[static_cast<size_t>(s)];
         slot < limit[static_cast<size_t>(s)]; ++slot) {
      for (int64_t k = 0; k < sub_w; ++k) {
        idx_flat[slot * sub_w + k] =
            static_cast<int32_t>(sec_sizes[s]);
      }
      sub_dst_flat[slot] = static_cast<int32_t>(num_rows);
    }
  }
  return kOk;
}

// ---------------------------------------------------------------------------
// Block-dense tile planning (ops/blockdense.py plan_blocks): the
// occupied-tile census and the A-table/residual fill as O(E) CSR
// walks.  The numpy pipeline (argsort + unique over E keys) takes
// ~15 minutes at Reddit scale — far too slow to fit a bench window —
// these passes take seconds.  Same two-pass caller-allocates shape as
// the sectioned prep above.
// ---------------------------------------------------------------------------

// (key, count) per occupied [block x block] tile, key ascending
// (key = dst_tile * n_src_tiles + src_tile, where n_src_tiles covers
// num_cols — the source space may be wider than the dst rows, e.g.
// the distributed planner's gathered coordinates).  Counts include
// every edge
// of the tile (saturation is the fill pass's business).  Writes at
// most `cap` rows; returns the TOTAL occupied-tile count (a result
// > cap means the output is truncated and the caller must retry with
// more room), or kErrValue for out-of-range columns.
int64_t roc_block_counts(const int64_t* row_ptr, const int32_t* col,
                         int64_t num_rows, int64_t num_cols,
                         int64_t block,
                         int64_t* keys, int64_t* counts, int64_t cap) {
  if (block <= 0 || num_cols <= 0) return kErrValue;
  int64_t n_tiles = (num_rows + block - 1) / block;
  int64_t n_src_tiles = (num_cols + block - 1) / block;
  std::vector<int64_t> cnt(static_cast<size_t>(n_src_tiles), 0);
  std::vector<int64_t> touched;
  int64_t nnz = 0;
  for (int64_t t = 0; t < n_tiles; ++t) {
    int64_t lo = t * block;
    int64_t hi = std::min(num_rows, lo + block);
    touched.clear();
    for (int64_t v = lo; v < hi; ++v) {
      for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; ++e) {
        int64_t s = col[e] / block;
        if (col[e] < 0 || s >= n_src_tiles) return kErrValue;
        if (cnt[static_cast<size_t>(s)]++ == 0) touched.push_back(s);
      }
    }
    std::sort(touched.begin(), touched.end());
    for (int64_t s : touched) {
      if (nnz < cap) {
        keys[nnz] = t * n_src_tiles + s;
        counts[nnz] = cnt[static_cast<size_t>(s)];
      }
      ++nnz;
      cnt[static_cast<size_t>(s)] = 0;
    }
  }
  return nnz;
}

// Fill pass: dense_keys is the planner's ASCENDING selection of tile
// keys; `a` is the zeroed uint8 [nblk * block * block] multiplicity
// table.  Edges in selected tiles increment their slot (saturating at
// 255 — overflow duplicates spill to the residual, keeping the
// semantics exact); everything else lands in the residual dst-major
// CSR (res_ptr [num_rows + 1], res_col capacity res_cap, original
// per-row edge order preserved).  Returns the residual edge count, or
// kErrValue on out-of-range columns / capacity overflow.
int64_t roc_block_fill(const int64_t* row_ptr, const int32_t* col,
                       int64_t num_rows, int64_t num_cols,
                       int64_t block,
                       const int64_t* dense_keys, int64_t nblk,
                       uint8_t* a, int64_t* res_ptr, int32_t* res_col,
                       int64_t res_cap) {
  if (block <= 0 || num_cols <= 0) return kErrValue;
  int64_t n_tiles = (num_rows + block - 1) / block;
  int64_t n_src_tiles = (num_cols + block - 1) / block;
  std::vector<int64_t> blk_of(static_cast<size_t>(n_src_tiles), -1);
  int64_t res_n = 0;
  int64_t k_lo = 0;
  for (int64_t t = 0; t < n_tiles; ++t) {
    int64_t k_hi = k_lo;
    while (k_hi < nblk && dense_keys[k_hi] < (t + 1) * n_src_tiles)
      ++k_hi;
    for (int64_t i = k_lo; i < k_hi; ++i) {
      blk_of[static_cast<size_t>(dense_keys[i] % n_src_tiles)] = i;
    }
    int64_t lo = t * block;
    int64_t hi = std::min(num_rows, lo + block);
    for (int64_t v = lo; v < hi; ++v) {
      res_ptr[v] = res_n;
      for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; ++e) {
        int64_t s = col[e] / block;
        if (col[e] < 0 || s >= n_src_tiles) return kErrValue;
        int64_t b = blk_of[static_cast<size_t>(s)];
        if (b >= 0) {
          uint8_t* slot = a + (b * block + (v - lo)) * block
                            + (col[e] - s * block);
          if (*slot < 255) {
            ++*slot;
            continue;
          }
        }
        if (res_n >= res_cap) return kErrValue;
        res_col[res_n++] = col[e];
      }
    }
    for (int64_t i = k_lo; i < k_hi; ++i) {
      blk_of[static_cast<size_t>(dense_keys[i] % n_src_tiles)] = -1;
    }
    k_lo = k_hi;
  }
  res_ptr[num_rows] = res_n;
  return res_n;
}

// ---------------------------------------------------------------------------
// Label propagation (core/reorder.py lpa_order): one ASYNCHRONOUS
// sweep over an undirected neighbor CSR, in increasing vertex order.
// labels_out starts as a copy of labels and every vote READS
// labels_out, so vertex v sees the already-updated labels of
// vertices < v.  labels_out[v] = the most frequent label among v's
// neighbors, ties -> smallest label; isolated vertices keep theirs.
// Returns the number of vertices whose final label differs from the
// entry label (the caller iterates to convergence).
//
// Asynchrony is load-bearing, not an optimization: fully-synchronous
// LPA 2-cycles (a star flips center<->leaf labels forever, so a
// convergence test never fires and the result depends on sweep-count
// parity), and no fixed vertex bipartition fixes that (same-class
// cycles survive).  The async rule is cycle-free by a lexicographic
// potential: every change either strictly raises the vertex's
// neighbor-agreement count or keeps it equal while strictly lowering
// the label (smallest-among-maxima tie rule), so sweeps terminate.
// The numpy fallback replays the identical vertex order — results
// are tested equal.
// ---------------------------------------------------------------------------

int64_t roc_lpa_iterate(const int64_t* nbr_ptr, const int32_t* nbr,
                        int64_t num_nodes, const int32_t* labels,
                        int32_t* labels_out) {
  std::vector<int32_t> scratch;
  int64_t changed = 0;
  std::copy(labels, labels + num_nodes, labels_out);
  for (int64_t v = 0; v < num_nodes; ++v) {
    int64_t lo = nbr_ptr[v], hi = nbr_ptr[v + 1];
    if (hi <= lo) {
      continue;
    }
    scratch.clear();
    for (int64_t e = lo; e < hi; ++e) {
      if (nbr[e] < 0 || nbr[e] >= num_nodes) return kErrValue;
      scratch.push_back(labels_out[nbr[e]]);
    }
    std::sort(scratch.begin(), scratch.end());
    int32_t best = scratch[0];
    int64_t best_n = 0;
    const int64_t n = static_cast<int64_t>(scratch.size());
    int64_t i = 0;
    while (i < n) {
      int64_t j = i;
      while (j < n && scratch[j] == scratch[i]) ++j;
      if (j - i > best_n) {
        best_n = j - i;
        best = scratch[i];
      }
      i = j;
    }
    labels_out[v] = best;
    if (best != labels[v]) ++changed;
  }
  return changed;
}

}  // extern "C"
