"""Perf-regression sentinel: the automated eye on the BENCH trajectory.

Five rounds of ``BENCH_r0*.json`` history sat on disk with nothing
watching them — r01–r05 all burned chip deadline on the same
compile-stall failure mode before a human noticed the pattern.  This
module turns the trajectory into a gate: given the recorded rounds
(and optionally a live run's metrics JSONL), it flags step-time /
compile-time / overlap_frac excursions beyond noise and exits nonzero
so CI, the bench probe preflight, and the measurement chains refuse
to ship a silent regression.

Detection is robust-statistics, with explicit small-sample rules:

- ``n == 0`` history → ``no_history`` (pass: nothing to regress
  against);
- ``n < 3`` → a median exists but no spread estimate: flag only past
  ``SMALL_SAMPLE_FACTOR``× the median (a 2× step-time regression
  bites, round-over-round tunnel noise does not);
- ``n >= 3`` → median + MAD: flag past
  ``median + max(MAD_K * 1.4826 * MAD, REL_FLOOR * median)``
  (the relative floor keeps a zero-spread history from flagging
  measurement jitter).

Step times only compare like with like: rounds whose recorded dtype
differs from the current run's are excluded (a mixed-precision round
is ~3× an fp32 one by design, not by regression).

Stdlib-only *reader* (same contract as report.py/timeline.py): no
backend, runs on artifacts from dead runs, works as a plain script on
a box without jax.  ``python -m roc_tpu.sentinel`` is the packaged
entry point; ``--json`` prints one machine-readable line for CI and
the bench probe.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))

BENCH_GLOB = "BENCH_r*.json"

# n >= 3: flag past median + max(MAD_K * sigma, REL_FLOOR * median)
MAD_K = 4.0
REL_FLOOR = 0.25
# n in {1, 2}: no spread estimate — flag only a gross excursion
SMALL_SAMPLE_FACTOR = 1.5
# serve-availability rates (shed/error/availability) are legitimately
# 0.0 or 1.0 across a healthy history — a pure relative bound would
# make them either unflaggable (zeros filtered) or hair-trigger
# (bound == median == 0), so they carry an ABSOLUTE slack floor: a
# shed/error rate may drift this many percentage points past the
# history median (availability: below it) before the gate bites
RATE_ABS_FLOOR = 0.05


def _median(vals: List[float]) -> float:
    vs = sorted(vals)
    n = len(vs)
    return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])


def detect(history: List[Optional[float]], current: Optional[float],
           higher_is_better: bool = False,
           mad_k: float = MAD_K, rel_floor: float = REL_FLOOR,
           small_factor: float = SMALL_SAMPLE_FACTOR,
           allow_zero: bool = False,
           abs_floor: float = 0.0) -> Dict[str, Any]:
    """One metric's verdict dict: ``verdict`` in {no_data, no_history,
    ok, regression} plus the numbers behind it (median, bound, n).

    ``allow_zero`` admits 0.0 as legitimate history (rates); purely
    relative slack collapses at a zero median, so rate metrics pass an
    ``abs_floor`` — the bound never sits closer than that absolute
    margin to the median (see :data:`RATE_ABS_FLOOR`)."""
    out: Dict[str, Any] = {"current": current,
                           "higher_is_better": higher_is_better}
    if current is None:
        out.update(verdict="no_data", n=0)
        return out
    hist = [float(v) for v in history
            if isinstance(v, (int, float))
            and (v > 0 or (allow_zero and v >= 0))]
    out["n"] = len(hist)
    if not hist:
        out["verdict"] = "no_history"
        return out
    med = _median(hist)
    out["median"] = round(med, 4)
    if len(hist) < 3:
        # small-sample rule: a median but no honest spread estimate
        bound = (med / small_factor - abs_floor if higher_is_better
                 else med * small_factor + abs_floor)
        out["rule"] = f"small_sample_{small_factor}x"
    else:
        sigma = 1.4826 * _median([abs(v - med) for v in hist])
        slack = max(mad_k * sigma, rel_floor * med, abs_floor)
        bound = med - slack if higher_is_better else med + slack
        out["rule"] = f"median_mad_k{mad_k:g}"
        out["sigma"] = round(sigma, 4)
    out["bound"] = round(bound, 4)
    worse = (current < bound) if higher_is_better else (current > bound)
    out["verdict"] = "regression" if worse else "ok"
    return out


# ------------------------------------------------- BENCH_*.json rounds

def load_bench_round(path: str) -> Dict[str, Any]:
    """One recorded round's comparable numbers.  Tolerates both the
    driver wrapper shape (``{"parsed": {...}, "tail": ...}``) and a
    bare headline line; missing metrics are None, never an error —
    the r01–r04 all-null rounds are legitimate history."""
    out: Dict[str, Any] = {"path": os.path.basename(path),
                           "step_ms": None, "compile_s": None,
                           "overlap_frac": None, "serve_p50_ms": None,
                           "serve_p99_ms": None,
                           "serve_qps": None, "serve_shed_rate": None,
                           "serve_error_rate": None,
                           "serve_availability": None,
                           "serve_slo_ok": None,
                           "serve_table_bytes": None,
                           "serve_quant_drift": None,
                           "serve_shard_table_bytes": None,
                           "serve_gather_p50_ms": None,
                           "ckpt_save_ms": None,
                           "ckpt_block_ms": None,
                           "mesh_epoch_ratio": None,
                           "dtype": None, "stage": None}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return out
    if not isinstance(doc, dict):
        return out
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else doc
    if not isinstance(parsed, dict):
        return out
    val = parsed.get("value")
    if isinstance(val, (int, float)) and parsed.get("unit") == "ms":
        out["step_ms"] = float(val)
    # serve rows (bench.py serve stage, PR 11): p50 request latency
    # and sustained QPS of the precomputed-propagation backend — the
    # serving tier's trajectory is gated exactly like epoch time.
    # The availability triple (PR 13) rides the same headline line:
    # shed/error rates and completed-over-submitted availability of
    # the serve stage's load run.
    # checkpoint-cost columns (ISSUE 15): the async save's wall time
    # and its step-path blocked time ride the headline exactly like
    # the serve columns — both gated lower-better
    # PR 17 adds the windowed tail latency (serve_p99_ms, from the
    # registry's log-bucket histogram) and the SLO-smoke verdict
    # (serve_slo_ok, 1.0 = Router.health() green) — rounds recorded
    # before PR 17 simply lack the keys and stay None (no_data)
    # PR 19 adds the quantized-serving pair: serve_table_bytes (the
    # int8 artifact's propagation-table bytes, lower-better — a
    # regression means the shrink was lost) and serve_quant_drift
    # (the gate's relative max |Δlogit|, lower-better)
    # PR 20 adds the sharded-serving pair: serve_shard_table_bytes
    # (one replica's slice bytes, lower-better — a regression means
    # the slicing stopped shrinking the per-replica footprint) and
    # serve_gather_p50_ms (the cross-shard gather leg's p50,
    # lower-better — the request-path cost of the slicing)
    for k in ("serve_p50_ms", "serve_p99_ms", "serve_qps",
              "serve_shed_rate", "serve_error_rate",
              "serve_availability", "serve_slo_ok",
              "serve_table_bytes", "serve_quant_drift",
              "serve_shard_table_bytes", "serve_gather_p50_ms",
              "ckpt_save_ms", "ckpt_block_ms"):
        if isinstance(parsed.get(k), (int, float)):
            out[k] = float(parsed[k])
    out["dtype"] = parsed.get("dtype")
    out["stage"] = parsed.get("stage")
    stages = parsed.get("stages")
    if isinstance(stages, dict):
        for name in ("full", "small"):
            st = stages.get(name)
            if isinstance(st, dict) and \
                    isinstance(st.get("compile_s"), (int, float)):
                out["compile_s"] = float(st["compile_s"])
                break
        # streamed-tier overlap lives in the micro stage's
        # stream:prefetch row (bench.py child_micro) — the prefetch
        # row is the measured overlap; any other row with the field
        # serves as fallback
        micro = stages.get("micro")
        impls = (micro.get("impls")
                 if isinstance(micro, dict) else None)
        if isinstance(impls, dict):
            rows = [impls.get("stream:prefetch")] + list(impls.values())
            for row in rows:
                if isinstance(row, dict) and \
                        isinstance(row.get("overlap_frac"),
                                   (int, float)):
                    out["overlap_frac"] = float(row["overlap_frac"])
                    break
            # 2-D mesh race (ISSUE 16): best-2-D / 1-D epoch ratio
            # from the micro stage's mesh:2d row, gated lower-better
            # — a PR that slows the model-sharded step relative to
            # the 1-D mesh regresses here first
            mesh = impls.get("mesh:2d")
            if isinstance(mesh, dict) and \
                    isinstance(mesh.get("mesh_epoch_ratio"),
                               (int, float)):
                out["mesh_epoch_ratio"] = float(
                    mesh["mesh_epoch_ratio"])
    return out


def bench_history(pattern: str) -> List[Dict[str, Any]]:
    """Rounds matching ``pattern``, in filename (round) order."""
    return [load_bench_round(p) for p in sorted(_glob.glob(pattern))]


# ----------------------------------------------- metrics-JSONL current

def _load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Tolerant JSONL reader (duplicated from obs/timeline.py on
    purpose: this module must run as a plain package-free script)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def metrics_summary(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """A live run's comparable numbers from its metrics JSONL: the
    median steady ``epoch_ms`` (records that folded a compile lap in
    are excluded), the worst ``compile_ms``, and the median
    ``overlap_frac`` (streamed tiers only)."""
    steady = [float(r["epoch_ms"]) for r in records
              if isinstance(r.get("epoch_ms"), (int, float))
              and r.get("compile_ms") is None]
    compiles = [float(r["compile_ms"]) for r in records
                if isinstance(r.get("compile_ms"), (int, float))]
    overlap = [float(r["overlap_frac"]) for r in records
               if isinstance(r.get("overlap_frac"), (int, float))]
    return {
        "step_ms": round(_median(steady), 3) if steady else None,
        "compile_s": (round(max(compiles) / 1e3, 3)
                      if compiles else None),
        "overlap_frac": (round(_median(overlap), 4)
                         if overlap else None),
        "n_records": len(records),
    }


# ---------------------------------------------------------- the gate

def check_run(rounds: List[Dict[str, Any]],
              current: Dict[str, Any]) -> Dict[str, Any]:
    """Hold ``current`` (step_ms / compile_s / overlap_frac, plus an
    optional dtype for like-with-like filtering) against the recorded
    rounds.  Returns ``{"checks": {...}, "regressed": [...],
    "ok": bool}``."""
    dtype = current.get("dtype")
    step_hist = [r.get("step_ms") for r in rounds
                 if dtype is None or r.get("dtype") in (None, dtype)]
    checks = {
        "step_time_ms": detect(step_hist, current.get("step_ms")),
        "compile_time_s": detect([r.get("compile_s") for r in rounds],
                                 current.get("compile_s")),
        "overlap_frac": detect([r.get("overlap_frac") for r in rounds],
                               current.get("overlap_frac"),
                               higher_is_better=True),
        "serve_p50_ms": detect([r.get("serve_p50_ms") for r in rounds],
                               current.get("serve_p50_ms")),
        # windowed tail latency (PR 17): the registry histogram's p99
        # over the stats window, gated lower-better like the median
        "serve_p99_ms": detect([r.get("serve_p99_ms") for r in rounds],
                               current.get("serve_p99_ms")),
        "serve_qps": detect([r.get("serve_qps") for r in rounds],
                            current.get("serve_qps"),
                            higher_is_better=True),
        # availability triple: rates are legitimately 0.0/1.0, so
        # they run with allow_zero + the absolute slack floor
        "serve_shed_rate": detect(
            [r.get("serve_shed_rate") for r in rounds],
            current.get("serve_shed_rate"), allow_zero=True,
            abs_floor=RATE_ABS_FLOOR),
        "serve_error_rate": detect(
            [r.get("serve_error_rate") for r in rounds],
            current.get("serve_error_rate"), allow_zero=True,
            abs_floor=RATE_ABS_FLOOR),
        "serve_availability": detect(
            [r.get("serve_availability") for r in rounds],
            current.get("serve_availability"),
            higher_is_better=True, allow_zero=True,
            abs_floor=RATE_ABS_FLOOR),
        # SLO-smoke verdict (PR 17): 1.0 = Router.health() green on
        # the quiet load-gen pass, 0.0 = an objective in breach — a
        # binary gated higher-better (a healthy history of 1.0s makes
        # any 0.0 bite via the relative floor)
        "serve_slo_ok": detect(
            [r.get("serve_slo_ok") for r in rounds],
            current.get("serve_slo_ok"),
            higher_is_better=True, allow_zero=True,
            abs_floor=RATE_ABS_FLOOR),
        # quantized serving (PR 19): the int8 artifact's propagation
        # table bytes, lower-better — a regression means the export
        # lost the shrink (e.g. the quant branch silently fell back
        # to fp32 tables)
        "serve_table_bytes": detect(
            [r.get("serve_table_bytes") for r in rounds],
            current.get("serve_table_bytes")),
        # ... and the drift gate's relative max |Δlogit|, lower-better;
        # healthy rounds sit well under the gate so an inflated round
        # bites via the relative floor (0.0 is legitimate → allow_zero)
        "serve_quant_drift": detect(
            [r.get("serve_quant_drift") for r in rounds],
            current.get("serve_quant_drift"), allow_zero=True,
            abs_floor=RATE_ABS_FLOOR),
        # sharded serving (PR 20): one replica's slice bytes,
        # lower-better — a regression means the shard plan stopped
        # shrinking the per-replica footprint (halo bloat, a slice
        # that silently fell back to the full table)
        "serve_shard_table_bytes": detect(
            [r.get("serve_shard_table_bytes") for r in rounds],
            current.get("serve_shard_table_bytes")),
        # ... and the cross-shard gather leg's p50, lower-better —
        # the request-path price of not holding the whole table,
        # gated exactly like the request p50
        "serve_gather_p50_ms": detect(
            [r.get("serve_gather_p50_ms") for r in rounds],
            current.get("serve_gather_p50_ms")),
        # checkpoint v3 (ISSUE 15): async save wall + step-path
        # blocked time, lower-better — a PR that re-synchronizes the
        # save path (or bloats the snapshot) regresses here first
        "ckpt_save_ms": detect([r.get("ckpt_save_ms") for r in rounds],
                               current.get("ckpt_save_ms")),
        "ckpt_block_ms": detect(
            [r.get("ckpt_block_ms") for r in rounds],
            current.get("ckpt_block_ms")),
        # 2-D mesh (ISSUE 16): the best-2-D-over-1-D epoch ratio,
        # lower-better — ratios sit near 1.0, so the absolute floor
        # keeps run-to-run noise from tripping the gate
        "mesh_epoch_ratio": detect(
            [r.get("mesh_epoch_ratio") for r in rounds],
            current.get("mesh_epoch_ratio"), allow_zero=True,
            abs_floor=RATE_ABS_FLOOR),
    }
    regressed = [name for name, v in checks.items()
                 if v["verdict"] == "regression"]
    return {"checks": checks, "regressed": regressed,
            "ok": not regressed,
            "history_rounds": [r["path"] for r in rounds]}


def bench_verdict(value_ms: Optional[float],
                  dtype: Optional[str] = None,
                  compile_s: Optional[float] = None,
                  bench_dir: Optional[str] = None,
                  stage: Optional[str] = None) -> Dict[str, Any]:
    """Compact verdict for the bench headline line (bench.py records
    it into BENCH_*.json): the live measurement vs the checked-in
    round history.  ``stage`` filters the rounds like dtype does —
    a small-stage epoch must never be scored against full-scale
    history (or vice versa).  Import-light — the bench parent calls
    this under its jax-free namespace stub."""
    pattern = os.path.join(bench_dir or _REPO_ROOT, BENCH_GLOB)
    rounds = [r for r in bench_history(pattern)
              if stage is None or r.get("stage") in (None, stage)]
    res = check_run(rounds,
                    {"step_ms": value_ms, "compile_s": compile_s,
                     "dtype": dtype})
    step = res["checks"]["step_time_ms"]
    out = {"verdict": step["verdict"], "n_history": step.get("n", 0)}
    for k in ("median", "bound", "rule"):
        if k in step:
            out[k] = step[k]
    if res["regressed"]:
        out["regressed"] = res["regressed"]
        out["verdict"] = "regression"
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="roc_tpu.sentinel", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--bench-glob", default=None,
                    help="BENCH round files (default: "
                         f"{BENCH_GLOB} in the repo root)")
    ap.add_argument("--metrics", default=None,
                    help="a live run's metrics JSONL: its steady "
                         "epoch_ms / compile_ms / overlap_frac are "
                         "the CURRENT numbers, checked against the "
                         "whole BENCH history")
    ap.add_argument("--dtype", default=None,
                    help="dtype of the current numbers (step-time "
                         "history is filtered to matching rounds; "
                         "default: the newest round's recorded dtype "
                         "in trajectory mode)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line on stdout "
                         "(CI / bench-probe preflight)")
    args = ap.parse_args(argv)

    pattern = args.bench_glob or os.path.join(_REPO_ROOT, BENCH_GLOB)
    rounds = bench_history(pattern)
    mode = "trajectory"
    if args.metrics:
        # live-run mode: the metrics file is current, every round is
        # history
        try:
            recs = _load_jsonl(args.metrics)
        except OSError as e:
            print(f"error: cannot read {args.metrics}: {e}",
                  file=sys.stderr)
            return 2
        current = metrics_summary(recs)
        current["dtype"] = args.dtype
        history = rounds
        mode = "metrics"
    else:
        # trajectory mode: the NEWEST round with any data is current,
        # prior rounds are history — the post-landing CI shape
        cur_idx = None
        for i in range(len(rounds) - 1, -1, -1):
            if any(rounds[i][k] is not None
                   for k in ("step_ms", "compile_s", "overlap_frac",
                             "serve_p50_ms", "serve_qps",
                             "serve_availability")):
                cur_idx = i
                break
        if cur_idx is None:
            payload = {"mode": mode, "ok": True,
                       "verdict": "no_data",
                       "rounds": [r["path"] for r in rounds]}
            print(json.dumps(payload) if args.json else
                  f"sentinel: no measurable rounds in {pattern} — "
                  f"nothing to gate")
            return 0
        cur = rounds[cur_idx]
        current = {"step_ms": cur["step_ms"],
                   "compile_s": cur["compile_s"],
                   "overlap_frac": cur.get("overlap_frac"),
                   "serve_p50_ms": cur.get("serve_p50_ms"),
                   "serve_p99_ms": cur.get("serve_p99_ms"),
                   "serve_qps": cur.get("serve_qps"),
                   "serve_shed_rate": cur.get("serve_shed_rate"),
                   "serve_error_rate": cur.get("serve_error_rate"),
                   "serve_availability": cur.get("serve_availability"),
                   "serve_slo_ok": cur.get("serve_slo_ok"),
                   "serve_table_bytes": cur.get("serve_table_bytes"),
                   "serve_quant_drift": cur.get("serve_quant_drift"),
                   "serve_shard_table_bytes":
                       cur.get("serve_shard_table_bytes"),
                   "serve_gather_p50_ms":
                       cur.get("serve_gather_p50_ms"),
                   "ckpt_save_ms": cur.get("ckpt_save_ms"),
                   "ckpt_block_ms": cur.get("ckpt_block_ms"),
                   "dtype": args.dtype or cur.get("dtype"),
                   "round": cur["path"]}
        history = rounds[:cur_idx]

    res = check_run(history, current)
    payload = {"mode": mode, "current": current, **res}
    if args.json:
        print(json.dumps(payload))
    else:
        print(f"sentinel ({mode}): current="
              + " ".join(f"{k}={current.get(k)}"
                         for k in ("step_ms", "compile_s",
                                   "overlap_frac", "round")
                         if current.get(k) is not None))
        for name, v in res["checks"].items():
            extra = "".join(
                f" {k}={v[k]}" for k in ("median", "bound", "n",
                                         "rule") if k in v)
            print(f"  {name}: {v['verdict']}{extra}")
        print("sentinel: "
              + ("OK — no regression beyond noise" if res["ok"] else
                 f"REGRESSION in {', '.join(res['regressed'])}"))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
