"""SLO engine: declarative objectives + multi-window burn-rate
alerting over the metrics registry.

An objective is a target on a *bad-event fraction* over a compliance
window W, declared either programmatically or via the one-line
grammar (the README's "SLO grammar"):

    availability(ok/requests) >= 0.999 over 60s
    p99(request_ms) <= 50ms over 60s

- **availability**: bad events are the requests that did not complete
  ok — ``bad = sum(total) - sum(ok)`` over the window, both read from
  registry :class:`~roc_tpu.obs.metrics_registry.Counter`\\ s.  The
  error budget is ``1 - target`` (0.999 → 0.1% of requests may fail).
- **latency quantile**: ``pQQ(hist) <= LIMITms`` means "at most
  ``1 - QQ`` of requests may exceed LIMIT" — bad events are the
  histogram samples above LIMIT, and the budget is ``1 - QQ`` (p99 →
  1%).  This is the windowed-fraction form of a quantile objective,
  which is what makes burn rates well-defined for latency too.

**Burn rate** = (bad fraction over an alert window) / budget: burn 1
means exactly spending the budget; burn 14 means at this rate the
window's budget is gone in W/14.  Alerting follows the SRE-workbook
multi-window shape scaled to serving-loop windows: each objective
evaluates a FAST rule (burn ≥ 14.4 over both W/6 and W/60) and a SLOW
rule (burn ≥ 6 over both W/2 and W/12) — the long window keeps alerts
from firing on one bad slice, the short window makes them reset
quickly once the incident clears.  Windows floor at one registry
slice.

Breaches are edge-triggered: entering breach emits a dated ``slo``
event (category documented in obs/events.py) and dumps the PR-9
flight recorder (``dump_flight_record`` — the last seconds of bus
telemetry around the breach); recovery back to within-objective emits
the matching ``recovered`` event.  :meth:`SloEngine.verdict` is the
machine-readable health surface ``Router.health()`` exposes to the
future autoscaler; :meth:`SloEngine.tick` is cheap enough to call
from a monitor loop (it self-limits to ``eval_interval_s``).

Stdlib-only, jax-free, compiles nothing.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .events import dump_flight_record, emit
from .metrics_registry import Counter, Histogram, MetricsRegistry

# the SRE-workbook multi-window burn-rate pairs, scaled to the
# objective's compliance window W: (long frac of W, short frac of W,
# burn threshold)
BURN_RULES = ((1.0 / 6.0, 1.0 / 60.0, 14.4),   # fast burn
              (1.0 / 2.0, 1.0 / 12.0, 6.0))    # slow burn

_SPEC_RE = re.compile(
    r"^\s*(?:(?P<name>[\w.-]+)\s*:)?\s*"
    r"(?:availability\s*\(\s*(?P<ok>[\w.]+)\s*/\s*(?P<total>[\w.]+)"
    r"\s*\)\s*>=\s*(?P<target>[0-9.]+)"
    r"|p(?P<q>\d{2})\s*\(\s*(?P<hist>[\w.]+)\s*\)\s*<=\s*"
    r"(?P<limit>[0-9.]+)\s*ms)"
    r"\s+over\s+(?P<window>[0-9.]+)\s*s\s*$")


class Slo:
    """One declarative objective.  ``kind`` is ``availability`` or
    ``latency``; see :func:`parse_slo` for the string form."""

    def __init__(self, name: str, kind: str, window_s: float,
                 target: float,
                 ok: Optional[str] = None,
                 total: Optional[str] = None,
                 hist: Optional[str] = None,
                 q: Optional[float] = None,
                 limit_ms: Optional[float] = None):
        if kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        self.name = name
        self.kind = kind
        self.window_s = float(window_s)
        self.target = float(target)
        self.ok = ok
        self.total = total
        self.hist = hist
        self.q = q
        self.limit_ms = limit_ms
        # error budget: tolerable bad-event fraction
        self.budget = (1.0 - self.target if kind == "availability"
                       else 1.0 - float(q or 0.0))
        if self.budget <= 0.0:
            raise ValueError(
                f"SLO {name!r} has zero error budget "
                f"(target {self.target}) — burn rate is undefined")

    def spec(self) -> str:
        if self.kind == "availability":
            return (f"availability({self.ok}/{self.total}) >= "
                    f"{self.target:g} over {self.window_s:g}s")
        return (f"p{int((self.q or 0) * 100)}({self.hist}) <= "
                f"{self.limit_ms:g}ms over {self.window_s:g}s")

    # ---------------------------------------------------- evaluation

    def _bad_frac(self, reg: MetricsRegistry,
                  window_s: float) -> float:
        if self.kind == "availability":
            total = reg.counter(self.total).sum_over(window_s)
            if total <= 0:
                return 0.0      # no traffic = no bad events
            ok = reg.counter(self.ok).sum_over(window_s)
            return max(0, total - ok) / total
        h = reg.histogram(self.hist)
        return h.frac_above(float(self.limit_ms), window_s)

    def _value(self, reg: MetricsRegistry) -> Optional[float]:
        """The objective's headline number over its own window —
        availability in [0, 1], or the latency quantile in ms."""
        if self.kind == "availability":
            return round(1.0 - self._bad_frac(reg, self.window_s), 6)
        v = reg.histogram(self.hist).quantile(
            float(self.q or 0.99), self.window_s)
        return round(v, 4) if v is not None else None

    def _has_traffic(self, reg: MetricsRegistry) -> bool:
        """Any lifetime events under the objective's denominator."""
        if self.kind == "availability":
            return reg.counter(self.total).sum_over(None) > 0
        return reg.histogram(self.hist).count_over(None) > 0


def parse_slo(spec: str) -> Slo:
    """Parse the one-line grammar (module docstring).  An optional
    leading ``name:`` labels the objective; otherwise the spec is its
    own name."""
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(
            f"cannot parse SLO spec {spec!r}; expected "
            f"'availability(ok/total) >= 0.999 over 60s' or "
            f"'p99(hist) <= 50ms over 60s'")
    g = m.groupdict()
    window_s = float(g["window"])
    if g["ok"]:
        return Slo(g["name"] or f"availability_{int(window_s)}s",
                   "availability", window_s, float(g["target"]),
                   ok=g["ok"], total=g["total"])
    q = int(g["q"]) / 100.0
    return Slo(g["name"] or f"p{g['q']}_{g['hist']}",
               "latency", window_s, q, hist=g["hist"], q=q,
               limit_ms=float(g["limit"]))


class SloEngine:
    """Continuous evaluation of objectives against a registry."""

    def __init__(self, registry: MetricsRegistry,
                 slos: Sequence[Any],
                 component: str = "serve",
                 eval_interval_s: float = 0.25,
                 flight_record: bool = True,
                 on_breach: Optional[Callable[[Dict[str, Any]], None]]
                 = None,
                 warmup_s: float = 2.0,
                 now: Callable[[], float] = time.monotonic):
        self.reg = registry
        self.slos: List[Slo] = [s if isinstance(s, Slo)
                                else parse_slo(s) for s in slos]
        self.component = component
        self.eval_interval_s = float(eval_interval_s)
        self.flight_record = flight_record
        self.on_breach = on_breach
        # availability counts a request at submit but its ok only at
        # completion, so the very first evaluations after traffic
        # starts see bad_frac ~ 1 over a tiny sample — rules may not
        # fire until traffic has flowed for warmup_s
        self.warmup_s = float(warmup_s)
        self._t_traffic: Optional[float] = None
        self._now = now
        self._lock = threading.Lock()
        self._last_eval = 0.0
        self._state: Dict[str, str] = {s.name: "ok"
                                       for s in self.slos}
        self._last_verdict: Optional[Dict[str, Any]] = None

    # ----------------------------------------------------- evaluation

    def _eval_one(self, slo: Slo) -> Dict[str, Any]:
        slice_s = self.reg.slice_s
        burns = []
        firing = False
        for long_f, short_f, thr in BURN_RULES:
            w_long = max(slice_s, slo.window_s * long_f)
            w_short = max(slice_s, slo.window_s * short_f)
            b_long = slo._bad_frac(self.reg, w_long) / slo.budget
            b_short = slo._bad_frac(self.reg, w_short) / slo.budget
            rule_fires = b_long >= thr and b_short >= thr
            firing = firing or rule_fires
            burns.append({"window_s": round(w_long, 2),
                          "short_s": round(w_short, 2),
                          "burn": round(b_long, 2),
                          "burn_short": round(b_short, 2),
                          "threshold": thr, "firing": rule_fires})
        bad_w = slo._bad_frac(self.reg, slo.window_s)
        compliant = bad_w <= slo.budget
        return {"name": slo.name, "kind": slo.kind,
                "spec": slo.spec(),
                "window_s": slo.window_s,
                "value": slo._value(self.reg),
                "target": (slo.target if slo.kind == "availability"
                           else slo.limit_ms),
                "bad_frac": round(bad_w, 6),
                "budget": round(slo.budget, 6),
                "burn": max(b["burn"] for b in burns) if burns else 0,
                "burn_rules": burns,
                "firing": firing,
                "compliant": compliant}

    def evaluate(self) -> Dict[str, Any]:
        """Evaluate every objective NOW (no rate limit): emit breach/
        recovery transitions, return the verdict."""
        objectives = [self._eval_one(s) for s in self.slos]
        now = self._now()
        with self._lock:
            if self._t_traffic is None and any(
                    s._has_traffic(self.reg) for s in self.slos):
                self._t_traffic = now
            warmed = (self._t_traffic is not None
                      and now - self._t_traffic >= self.warmup_s)
        if not warmed:
            for ob in objectives:
                if ob["firing"]:
                    ob["firing"] = False
                    ob["warmup"] = True
        transitions = []
        with self._lock:
            for ob in objectives:
                prev = self._state.get(ob["name"], "ok")
                if prev == "ok" and ob["firing"]:
                    self._state[ob["name"]] = "breach"
                    transitions.append(("breach", ob))
                elif prev == "breach" and not ob["firing"] \
                        and ob["compliant"]:
                    self._state[ob["name"]] = "ok"
                    transitions.append(("recovered", ob))
            states = dict(self._state)
        for what, ob in transitions:
            worst = max(ob["burn_rules"],
                        key=lambda b: b["burn"])
            emit("slo",
                 f"SLO {what}: {ob['spec']} — burn "
                 f"{worst['burn']:.1f}x budget over "
                 f"{worst['window_s']:.0f}s "
                 f"(value {ob['value']}, target {ob['target']})",
                 kind=what, slo=ob["name"], component=self.component,
                 spec=ob["spec"], burn=worst["burn"],
                 burn_window_s=worst["window_s"],
                 value=ob["value"], target=ob["target"],
                 bad_frac=ob["bad_frac"], budget=ob["budget"])
            if what == "breach":
                if self.flight_record:
                    dump_flight_record(
                        f"slo breach {ob['name']}")
                if self.on_breach is not None:
                    try:
                        self.on_breach(ob)
                    except Exception:  # noqa: BLE001 - alerting must
                        pass           # never take down serving
        verdict = {"ok": all(st == "ok" for st in states.values())
                   and all(ob["compliant"] for ob in objectives),
                   "states": states,
                   "objectives": objectives}
        with self._lock:
            self._last_verdict = verdict
        return verdict

    def tick(self) -> Optional[Dict[str, Any]]:
        """Rate-limited evaluate() for monitor loops: no-op (returns
        the cached verdict) within ``eval_interval_s`` of the last
        evaluation."""
        now = self._now()
        with self._lock:
            if now - self._last_eval < self.eval_interval_s:
                return self._last_verdict
            self._last_eval = now
        return self.evaluate()

    def verdict(self) -> Dict[str, Any]:
        """The machine-readable health verdict (evaluates fresh)."""
        return self.evaluate()
