"""Low-overhead streaming metrics registry: the serving fleet's
*live* telemetry layer.

The event bus (obs/events.py) is a narrative stream — dated records a
post-mortem reads back.  A control loop (the SLO engine, the future
autoscaler, a ``watch``-ed dashboard) needs the other shape: *current
windowed rates* — "what is the shed rate over the last 60 s", "what is
p99 request latency right now" — cheap enough to record on the request
hot path.  Three metric kinds:

- :class:`Counter` — monotone event count.  ``inc()`` is O(1); reads
  give the lifetime ``total`` plus ``sum_over(window_s)`` /
  ``rate(window_s)`` over any window the slice ring still covers.
- :class:`Gauge` — last-write-wins scalar, with an optional EWMA
  (``ewma_alpha``) for step-time style smoothing.
- :class:`Histogram` — sliding-window quantiles over **fixed
  log-spaced buckets**: ``record()`` is O(1) (one log, one array
  increment — no sorting, no sample retention), and
  ``quantile(q, window_s)`` merges the ring slices covering the
  window.  Quantiles are bucket-resolution approximations: with the
  default ``per_decade=16`` a reported quantile is within one bucket,
  i.e. a factor of ``10**(1/16)`` ≈ 1.155, of the true value — plenty
  for burn-rate alerting and hedging thresholds, useless for
  microbenchmark deltas (those keep their exact sample lists).

Windowing is a shared time-sliced ring: each metric keeps
``n_slices`` buckets of ``slice_s`` seconds and lazily zeroes slices
as the clock advances past them — no background thread, no timers.  A
window query sums the slices that cover ``[now - window_s, now]``
(including the current partial slice), so the covered span is between
``window_s`` and ``window_s + slice_s``.

Deliberately stdlib-only and jax-free (the registry compiles
nothing); thread-safe per metric (one small lock each — recorders on
the request path never contend with snapshot readers for more than an
integer add).  ``MetricsRegistry.snapshot()`` is the JSON-able view
the SLO engine, ``Router.health()``, and ``python -m roc_tpu.report
--slo`` all read; ``dump(path)`` writes it atomically for the
``watch``-able dashboard.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

# default ring geometry: 1-second slices, 128 of them — window
# queries up to ~2 minutes, which covers every SLO window the serving
# loop evaluates (scale n_slices up for longer windows)
DEFAULT_SLICE_S = 1.0
DEFAULT_N_SLICES = 128

# default histogram bucket space: log-spaced from 1 µs to 10 min
# (in ms), 16 buckets per decade — latency-shaped, but any positive
# series fits (values clamp to the edge buckets)
DEFAULT_HIST_LO = 1e-3
DEFAULT_HIST_HI = 6e5
DEFAULT_PER_DECADE = 16


class _Sliced:
    """Shared time-sliced ring: lazy rotation, no threads."""

    def __init__(self, slice_s: float, n_slices: int,
                 now: Callable[[], float]):
        self.slice_s = float(slice_s)
        self.n_slices = int(n_slices)
        self._now = now
        self._cur = int(now() // self.slice_s)
        self._lock = threading.Lock()

    def _zero_slice(self, i: int) -> None:
        raise NotImplementedError

    def _advance_locked(self) -> int:
        """Rotate the ring up to the current slice; returns it."""
        s = int(self._now() // self.slice_s)
        d = s - self._cur
        if d > 0:
            for k in range(1, min(d, self.n_slices) + 1):
                self._zero_slice((self._cur + k) % self.n_slices)
            self._cur = s
        return self._cur

    def _window_slices(self, window_s: Optional[float]) -> int:
        if window_s is None:
            return self.n_slices
        return max(1, min(self.n_slices,
                          int(math.ceil(window_s / self.slice_s))))


class Counter(_Sliced):
    """Monotone event counter with windowed reads."""

    def __init__(self, name: str, slice_s: float = DEFAULT_SLICE_S,
                 n_slices: int = DEFAULT_N_SLICES,
                 now: Callable[[], float] = time.monotonic):
        super().__init__(slice_s, n_slices, now)
        self.name = name
        self.total = 0
        self._slices = [0] * self.n_slices

    def _zero_slice(self, i: int) -> None:
        self._slices[i] = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            cur = self._advance_locked()
            self._slices[cur % self.n_slices] += n
            self.total += n

    def sum_over(self, window_s: Optional[float] = None) -> int:
        """Events recorded in the trailing window (None = whole
        ring)."""
        k = self._window_slices(window_s)
        with self._lock:
            cur = self._advance_locked()
            return sum(self._slices[(cur - i) % self.n_slices]
                       for i in range(k))

    def rate(self, window_s: float) -> float:
        """Events/second over the trailing window."""
        return self.sum_over(window_s) / max(window_s, 1e-9)

    def snapshot(self, windows: Sequence[float]) -> Dict[str, Any]:
        return {"kind": "counter", "total": self.total,
                **{f"sum_{int(w)}s": self.sum_over(w)
                   for w in windows}}


class Gauge:
    """Last-write-wins scalar; optional EWMA smoothing."""

    def __init__(self, name: str, ewma_alpha: Optional[float] = None):
        self.name = name
        self._lock = threading.Lock()
        self._value: Optional[float] = None
        self._ewma: Optional[float] = None
        self._alpha = ewma_alpha
        self.n = 0

    def set(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._value = v
            self.n += 1
            if self._alpha is not None:
                self._ewma = (v if self._ewma is None else
                              self._alpha * v
                              + (1.0 - self._alpha) * self._ewma)

    @property
    def value(self) -> Optional[float]:
        return self._value

    @property
    def ewma(self) -> Optional[float]:
        return self._ewma if self._alpha is not None else self._value

    def snapshot(self, windows: Sequence[float]) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": "gauge", "value": self._value,
                               "n": self.n}
        if self._alpha is not None and self._ewma is not None:
            out["ewma"] = round(self._ewma, 6)
        return out


class Histogram(_Sliced):
    """Sliding-window quantiles over fixed log-spaced buckets."""

    def __init__(self, name: str, lo: float = DEFAULT_HIST_LO,
                 hi: float = DEFAULT_HIST_HI,
                 per_decade: int = DEFAULT_PER_DECADE,
                 slice_s: float = DEFAULT_SLICE_S,
                 n_slices: int = DEFAULT_N_SLICES,
                 now: Callable[[], float] = time.monotonic):
        super().__init__(slice_s, n_slices, now)
        self.name = name
        self.lo = float(lo)
        self.hi = float(hi)
        self.per_decade = int(per_decade)
        self._log_lo = math.log10(self.lo)
        self.n_buckets = int(math.ceil(
            (math.log10(self.hi) - self._log_lo)
            * self.per_decade)) + 1
        self._slices = [[0] * self.n_buckets
                        for _ in range(self.n_slices)]
        self._life = [0] * self.n_buckets
        self.total = 0
        self.sum = 0.0

    def _zero_slice(self, i: int) -> None:
        self._slices[i] = [0] * self.n_buckets

    def _bucket(self, v: float) -> int:
        if v <= self.lo:
            return 0
        b = int((math.log10(v) - self._log_lo) * self.per_decade)
        return min(b, self.n_buckets - 1)

    def bucket_value(self, b: int) -> float:
        """The geometric midpoint a bucket reports as its value."""
        return 10.0 ** (self._log_lo
                        + (b + 0.5) / self.per_decade)

    def bucket_lo(self, b: int) -> float:
        return 10.0 ** (self._log_lo + b / self.per_decade)

    def record(self, v: float) -> None:
        b = self._bucket(float(v))
        with self._lock:
            cur = self._advance_locked()
            self._slices[cur % self.n_slices][b] += 1
            self._life[b] += 1
            self.total += 1
            self.sum += float(v)

    def _merged(self, window_s: Optional[float]) -> List[int]:
        if window_s is None:
            with self._lock:
                return list(self._life)
        k = self._window_slices(window_s)
        with self._lock:
            cur = self._advance_locked()
            merged = [0] * self.n_buckets
            for i in range(k):
                sl = self._slices[(cur - i) % self.n_slices]
                for b, c in enumerate(sl):
                    if c:
                        merged[b] += c
            return merged

    def count_over(self, window_s: Optional[float] = None) -> int:
        return sum(self._merged(window_s))

    def quantile(self, q: float,
                 window_s: Optional[float] = None
                 ) -> Optional[float]:
        """Approximate q-quantile (geometric bucket midpoint) over
        the window; None when the window holds no samples."""
        merged = self._merged(window_s)
        n = sum(merged)
        if n == 0:
            return None
        target = q * n
        acc = 0
        for b, c in enumerate(merged):
            acc += c
            if acc >= target and c:
                return self.bucket_value(b)
        return self.bucket_value(self.n_buckets - 1)

    def frac_above(self, limit: float,
                   window_s: Optional[float] = None) -> float:
        """Fraction of windowed samples above ``limit`` — the SLO
        engine's bad-event fraction for latency objectives.  Bucket-
        resolution: a sample counts as above when its whole bucket
        sits at or above the bucket containing ``limit``'s midpoint."""
        merged = self._merged(window_s)
        n = sum(merged)
        if n == 0:
            return 0.0
        b_lim = self._bucket(float(limit))
        above = sum(c for b, c in enumerate(merged) if b > b_lim)
        return above / n

    def snapshot(self, windows: Sequence[float]) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "kind": "histogram", "total": self.total,
            "mean": (round(self.sum / self.total, 4)
                     if self.total else None)}
        for w in windows:
            n = self.count_over(w)
            out[f"n_{int(w)}s"] = n
            for q, label in ((0.50, "p50"), (0.95, "p95"),
                             (0.99, "p99")):
                v = self.quantile(q, w)
                out[f"{label}_{int(w)}s"] = (round(v, 4)
                                             if v is not None else None)
        return out


class MetricsRegistry:
    """Named factory + snapshot for a component's metrics.  Metric
    getters are get-or-create (idempotent by name), so call sites can
    resolve by name on the hot path without holding references."""

    def __init__(self, name: str = "",
                 slice_s: float = DEFAULT_SLICE_S,
                 n_slices: int = DEFAULT_N_SLICES,
                 now: Callable[[], float] = time.monotonic):
        self.name = name
        self.slice_s = float(slice_s)
        self.n_slices = int(n_slices)
        self._now = now
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, factory: Callable[[], Any],
             klass: type) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, klass):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {klass.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, lambda: Counter(
            name, self.slice_s, self.n_slices, self._now), Counter)

    def gauge(self, name: str,
              ewma_alpha: Optional[float] = None) -> Gauge:
        return self._get(name, lambda: Gauge(name, ewma_alpha), Gauge)

    def histogram(self, name: str, lo: float = DEFAULT_HIST_LO,
                  hi: float = DEFAULT_HIST_HI,
                  per_decade: int = DEFAULT_PER_DECADE) -> Histogram:
        return self._get(name, lambda: Histogram(
            name, lo, hi, per_decade, self.slice_s, self.n_slices,
            self._now), Histogram)

    def get(self, name: str) -> Optional[Any]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self, windows: Sequence[float] = (10.0, 60.0)
                 ) -> Dict[str, Any]:
        """JSON-able view of every metric: lifetime totals plus the
        windowed sums/quantiles the SLO engine and dashboard read."""
        with self._lock:
            items = list(self._metrics.items())
        return {"registry": self.name,
                "windows_s": [float(w) for w in windows],
                "metrics": {n: m.snapshot(windows)
                            for n, m in items}}

    def dump(self, path: str,
             windows: Sequence[float] = (10.0, 60.0),
             extra: Optional[Dict[str, Any]] = None) -> None:
        """Atomically write the snapshot (tmp + rename) — the
        ``watch -n1 python -m roc_tpu.report --slo <path>`` feed.
        Never raises: a telemetry write must not take down serving."""
        doc = self.snapshot(windows)
        doc["t"] = round(time.time(), 3)
        if extra:
            doc.update(extra)
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
