"""Run manifest: the event that makes a run self-describing.

Emitted once at trainer setup (both trainers), up front in the event
stream: what code (git sha, jax version), what hardware (device
topology), what data (V/E/name), and — most importantly — what the
framework DECIDED (resolved ``aggr_impl``/``aggr_fuse``/halo/
features/remat, memory-plan echo, bdense occupancy).  The scattered
stderr echoes stay (console sink), but the manifest is the one record
a post-mortem can trust to describe the run that actually executed,
not the flags that were requested.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .events import _jsonable, emit

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def git_sha(repo_root: str = _REPO_ROOT) -> Optional[str]:
    """HEAD commit sha without shelling out (works in sandboxes where
    git itself is absent); None when not a git checkout."""
    try:
        head_path = os.path.join(repo_root, ".git", "HEAD")
        with open(head_path) as f:
            head = f.read().strip()
        if head.startswith("ref:"):
            ref = head.split(None, 1)[1]
            ref_path = os.path.join(repo_root, ".git", *ref.split("/"))
            if os.path.exists(ref_path):
                with open(ref_path) as f:
                    return f.read().strip()
            packed = os.path.join(repo_root, ".git", "packed-refs")
            with open(packed) as f:
                for line in f:
                    if line.strip().endswith(ref):
                        return line.split()[0]
            return None
        return head
    except OSError:
        return None


def _config_dict(config) -> Dict[str, Any]:
    import dataclasses
    d = dataclasses.asdict(config)
    # dtypes serialize by name, not repr of the type object
    for k in ("dtype", "compute_dtype"):
        if d.get(k) is not None:
            try:
                import jax.numpy as jnp
                d[k] = str(jnp.dtype(d[k]))
            except Exception:  # noqa: BLE001 - name is best-effort
                d[k] = str(d[k])
    return _jsonable(d)


def run_manifest(config=None, dataset=None, model=None,
                 num_parts: int = 1,
                 extra: Optional[Dict[str, Any]] = None,
                 console: bool = True) -> Dict[str, Any]:
    """Assemble + emit the ``manifest`` event; returns the fields.

    Everything is best-effort: a missing backend or detached checkout
    degrades to nulls, never to an exception at trainer setup."""
    fields: Dict[str, Any] = {"git_sha": git_sha()}
    try:
        import jax
        fields["jax_version"] = jax.__version__
        fields["process_index"] = jax.process_index()
        fields["process_count"] = jax.process_count()
        # pin the clock tuple's proc for every later event: the env
        # default (JAX_PROCESS_ID) is right under explicit launchers,
        # but jax's own process_index is authoritative once known
        from .events import set_clock_identity
        set_clock_identity(proc=fields["process_index"])
        devs = jax.devices()
        fields["device_count"] = len(devs)
        fields["platform"] = devs[0].platform if devs else None
        fields["device_kinds"] = sorted(
            {d.device_kind for d in devs})
    except Exception as e:  # noqa: BLE001 - backendless manifest
        fields["backend_error"] = repr(e)
    if config is not None:
        fields["config"] = _config_dict(config)
        fields["resolved"] = {
            "aggr_impl": getattr(config, "aggr_impl", None),
            "aggr_fuse": getattr(config, "aggr_fuse", None),
            "halo": getattr(config, "halo", None),
            "features": getattr(config, "features", None),
            "remat": getattr(config, "remat", None),
            "num_parts": num_parts,
        }
    if dataset is not None:
        g = dataset.graph
        fields["dataset"] = {"name": dataset.name,
                             "num_nodes": int(g.num_nodes),
                             "num_edges": int(g.num_edges),
                             "num_classes": int(dataset.num_classes)}
    if model is not None:
        try:
            fields["model"] = {
                "ops": [op.kind for op in model._ops],
                "fused_aggregates": model.num_fused_aggregates(),
            }
        except Exception:  # noqa: BLE001 - shape of _ops may evolve
            pass
    if extra:
        fields.update(_jsonable(extra))
    msg = (f"run manifest: platform={fields.get('platform')} "
           f"devices={fields.get('device_count')} "
           f"jax={fields.get('jax_version')} "
           f"sha={(fields.get('git_sha') or 'none')[:12]}")
    emit("manifest", msg, console=console, **fields)
    return fields
