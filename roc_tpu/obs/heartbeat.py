"""Stall watchdog: periodic "still waiting in <stage>" events.

The round-5 bench stages all timed out silently at "claiming backend"
— a blank timeout is undiagnosable after the fact.  A
:class:`Heartbeat` wraps any potentially-hanging region (backend
claim, first compile, a bench stage child) and emits a ``stall``
event every ``interval_s`` from a daemon thread, so the artifact
records WHERE the time went and for how long, even when the region
never returns.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from .events import emit

# default watchdog period; bench/test harnesses tighten it via env
DEFAULT_INTERVAL_S = 30.0


def heartbeat_interval(default: float = DEFAULT_INTERVAL_S) -> float:
    try:
        return float(os.environ.get("ROC_TPU_HEARTBEAT_S", default))
    except ValueError:
        return default


class Heartbeat:
    """Context manager emitting ``stall`` events while the enclosed
    region runs.

    >>> with Heartbeat("claiming backend"):
    ...     dev = jax.devices()[0]

    The thread is a daemon (a wedged region killed by SIGTERM must not
    be kept alive by its own watchdog) and fires only AFTER the first
    full interval — a fast region emits nothing.  ``cancel()`` (or
    normal exit) stops it; the event count is exposed as ``fired`` for
    tests and post-mortems.  An interval <= 0 (ROC_TPU_HEARTBEAT_S=0)
    disables the watchdog entirely — never a zero-wait spin loop."""

    def __init__(self, stage: str, interval_s: Optional[float] = None,
                 bus=None, **fields: Any):
        self.stage = stage
        self.interval_s = (heartbeat_interval() if interval_s is None
                           else float(interval_s))
        self.fired = 0
        self._fields = fields
        self._bus = bus
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.fired += 1
            elapsed = time.monotonic() - self._t0
            msg = (f"still waiting in {self.stage}, elapsed "
                   f"{elapsed:.0f}s")
            if self._bus is not None:
                self._bus.emit("stall", msg, stage=self.stage,
                               elapsed_s=round(elapsed, 1),
                               beat=self.fired, **self._fields)
            else:
                emit("stall", msg, stage=self.stage,
                     elapsed_s=round(elapsed, 1), beat=self.fired,
                     **self._fields)

    def start(self) -> "Heartbeat":
        self._t0 = time.monotonic()
        self._stop.clear()
        if self.interval_s <= 0:
            # the documented off switch: wait(0) would return
            # immediately and flood stderr + the JSONL artifact
            return self
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeat:{self.stage}",
            daemon=True)
        self._thread.start()
        return self

    def cancel(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.cancel()
