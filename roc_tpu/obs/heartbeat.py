"""Stall watchdog: periodic "still waiting in <stage>" events, with an
optional deadline that converts a silent hang into a raisable failure.

The round-5 bench stages all timed out silently at "claiming backend"
— a blank timeout is undiagnosable after the fact.  A
:class:`Heartbeat` wraps any potentially-hanging region (backend
claim, first compile, multihost setup collectives, a bench stage
child) and emits a ``stall`` event every ``interval_s`` from a daemon
thread, so the artifact records WHERE the time went and for how long,
even when the region never returns.

**Deadline promotion** (resilience PR): with ``ROC_TPU_STALL_TIMEOUT_S``
set (or ``deadline_s`` passed), a region that outlives the deadline is
*interrupted* — the watchdog delivers a real SIGINT to the main thread
(``pthread_kill``; a mere ``interrupt_main`` flag is never seen by a
thread blocked inside a C call) and the context manager converts the
resulting ``KeyboardInterrupt`` into a :class:`StallFailure`, which the recovery
loop (``resilience/recovery.py``) can checkpoint-restart instead of
letting the run die as a blank bench timeout.  Only armed when the
guarded region runs on the main thread (interrupting the main thread
on behalf of a worker-thread region would hit the wrong victim).

The watchdog's concurrency contract — a joined shutdown path, flag
publishes (never read-modify-writes) shared with the preemption
guard's signal handler, no lock held across the interrupt — is
enforced by roc-lint level six (``analysis/concurrency_lint.py``),
not just by this prose.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from .events import emit

# default watchdog period; bench/test harnesses tighten it via env
DEFAULT_INTERVAL_S = 30.0


class StallFailure(RuntimeError):
    """A watchdog-guarded region exceeded its stall deadline.  One of
    the recoverable failure classes (resilience/recovery.py
    RECOVERABLE): the recovery loop restores the last checkpoint and
    retries instead of dying as a silent hang."""


def heartbeat_interval(default: float = DEFAULT_INTERVAL_S) -> float:
    try:
        return float(os.environ.get("ROC_TPU_HEARTBEAT_S", default))
    except ValueError:
        return default


# the Heartbeat currently interrupting the main thread (deadline
# promotion).  interrupt_main simulates SIGINT: when the preemption
# guard (resilience/preempt.py) owns the SIGINT handler it must be
# able to tell a watchdog interrupt from a user Ctrl-C — it checks
# this flag and re-raises KeyboardInterrupt instead of going graceful.
_INTERRUPTING: Optional["Heartbeat"] = None


def stall_interrupt_pending() -> bool:
    return _INTERRUPTING is not None


def stall_timeout() -> Optional[float]:
    """The env-armed stall deadline in seconds, or None (off — the
    default: a deadline that fires during a legitimate first compile
    would be worse than the hang it guards against, so arming is an
    explicit harness decision)."""
    try:
        t = float(os.environ.get("ROC_TPU_STALL_TIMEOUT_S", 0.0))
    except ValueError:
        return None
    return t if t > 0 else None


class Heartbeat:
    """Context manager emitting ``stall`` events while the enclosed
    region runs.

    >>> with Heartbeat("claiming backend"):
    ...     dev = jax.devices()[0]

    The thread is a daemon (a wedged region killed by SIGTERM must not
    be kept alive by its own watchdog) and fires only AFTER the first
    full interval — a fast region emits nothing.  ``cancel()`` (or
    normal exit) stops it; the event count is exposed as ``fired`` for
    tests and post-mortems.  An interval <= 0 (ROC_TPU_HEARTBEAT_S=0)
    disables the periodic beats — never a zero-wait spin loop — but an
    armed deadline still runs.

    ``deadline_s`` (default: ``ROC_TPU_STALL_TIMEOUT_S``, off when
    unset) promotes the watchdog from observer to enforcer: past the
    deadline the region is interrupted and exits by raising
    :class:`StallFailure`."""

    def __init__(self, stage: str, interval_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 bus=None, **fields: Any):
        self.stage = stage
        self.interval_s = (heartbeat_interval() if interval_s is None
                           else float(interval_s))
        self.deadline_s = (stall_timeout() if deadline_s is None
                           else (float(deadline_s)
                                 if deadline_s > 0 else None))
        self.fired = 0
        self.deadline_hit = False
        self._fields = fields
        self._bus = bus
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0
        self._owner_is_main = False

    def _emit(self, msg: str, **fields: Any) -> None:
        if self._bus is not None:
            self._bus.emit("stall", msg, stage=self.stage, **fields)
        else:
            emit("stall", msg, stage=self.stage, **fields)

    def _wait_s(self) -> float:
        """Next watchdog wait: the beat interval, shortened so an
        armed deadline can fire on time (beats off -> deadline-only
        cadence).  Once the deadline HAS fired the cadence reverts to
        plain beats — never a sub-interval spin."""
        if self.deadline_s is None or self.deadline_hit:
            return self.interval_s
        left = max(0.1, self.deadline_s
                   - (time.monotonic() - self._t0))
        if self.interval_s <= 0:
            return left
        return min(self.interval_s, left)

    def _run(self) -> None:
        while not self._stop.wait(self._wait_s()):
            elapsed = time.monotonic() - self._t0
            if self.deadline_s is not None and not self.deadline_hit \
                    and elapsed >= self.deadline_s:
                if self._stop.is_set():
                    # region completed while we were deciding: a
                    # signal now would land OUTSIDE the with-block
                    return
                self.deadline_hit = True
                self._emit(f"stall deadline {self.deadline_s:.0f}s "
                           f"exceeded in {self.stage} (elapsed "
                           f"{elapsed:.0f}s) — interrupting",
                           elapsed_s=round(elapsed, 1),
                           deadline_s=self.deadline_s, **self._fields)
                # raise the main thread out of the hang; __exit__
                # converts the KeyboardInterrupt into StallFailure.
                # A REAL signal (pthread_kill), not interrupt_main:
                # the latter only sets a Python-level flag, which a
                # thread blocked inside a C call (time.sleep, a device
                # fetch) never reaches — the signal EINTRs the call.
                # The flag lets a SIGINT-owning preemption guard
                # route this interrupt through instead of handling
                # it as a graceful Ctrl-C.
                # crash flight recorder: the stall may still wedge the
                # process terminally (a C-blocked region that retries
                # EINTR never sees the interrupt), so the telemetry
                # window is persisted BEFORE the interrupt attempt
                from .events import dump_flight_record
                dump_flight_record(f"stall:{self.stage}")
                global _INTERRUPTING
                _INTERRUPTING = self
                import signal as _signal
                _signal.pthread_kill(threading.main_thread().ident,
                                     _signal.SIGINT)
                if self.interval_s <= 0:
                    return
                # keep beating: a C-blocked region that retries EINTR
                # internally (an XLA compile/rendezvous) never sees
                # the interrupt — the hang the deadline failed to
                # break must still leave dated evidence
                continue
            if self.interval_s > 0:
                self.fired += 1
                self._emit(f"still waiting in {self.stage}, elapsed "
                           f"{elapsed:.0f}s",
                           elapsed_s=round(elapsed, 1),
                           beat=self.fired, **self._fields)

    def start(self) -> "Heartbeat":
        self._t0 = time.monotonic()
        self._stop.clear()
        self._owner_is_main = (threading.current_thread()
                               is threading.main_thread())
        if not self._owner_is_main:
            # interrupt_main would hit the wrong victim — keep the
            # watchdog observational for worker-thread regions
            self.deadline_s = None
        if self.interval_s <= 0 and self.deadline_s is None:
            # the documented off switch: wait(0) would return
            # immediately and flood stderr + the JSONL artifact
            return self
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeat:{self.stage}",
            daemon=True)
        self._thread.start()
        return self

    def _shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _clear_pending(self) -> None:
        global _INTERRUPTING
        if _INTERRUPTING is self:
            _INTERRUPTING = None

    def cancel(self) -> None:
        self._shutdown()
        self._clear_pending()

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            self._shutdown()
            if self.deadline_hit and exc_type is not KeyboardInterrupt:
                # the region exited (cleanly OR with some other
                # exception) in the same instant the watchdog fired:
                # its SIGINT is already in flight — absorb it here
                # rather than letting it land at an arbitrary later
                # point, where a cleared pending-stall flag would let
                # a preemption guard misread it as a graceful Ctrl-C
                # (while the flag is still set, the guard routes it
                # through as KeyboardInterrupt)
                time.sleep(0.1)
        except KeyboardInterrupt:
            if not self.deadline_hit:
                raise   # a real Ctrl-C racing the shutdown
            # the watchdog's late interrupt landed somewhere inside
            # the shutdown/absorb window: swallowed either way — the
            # region itself already exited (an in-region interrupt
            # never reaches this try)
        finally:
            self._clear_pending()
        if self.deadline_hit and exc_type is KeyboardInterrupt:
            raise StallFailure(
                f"stalled in {self.stage}: exceeded the "
                f"{self.deadline_s:.0f}s deadline "
                f"(ROC_TPU_STALL_TIMEOUT_S)") from exc
