"""Compile observer: what XLA actually built, vs what we modeled.

Wraps a jitted step function so its first execution goes through the
explicit AOT path (``lower()`` then ``compile()``), capturing:

- lowering + compile wall time (the number the bench stages could
  never attribute: "claiming backend" vs "compiling" vs "running");
- ``cost_analysis()`` — flops and bytes accessed per step, the inputs
  to MFU/throughput derivation downstream;
- ``memory_analysis()`` — XLA's actual argument/output/temp sizes,
  whose sum approximates peak HBM for the executable;
- the delta between that actual peak and ``core/memory.py``'s modeled
  budget — warning loudly when the plan undershoots reality (the
  planner-vs-residency disagreement the round-5 advisor flagged).

Steady-state calls route through the compiled executable (the AOT
compile would otherwise be thrown away and paid twice).  Every
introspection step degrades gracefully: a backend without
``cost_analysis`` still trains, it just reports nulls
(tests/test_obs.py gates this).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

from .events import emit
from .heartbeat import Heartbeat


def leaf_struct(x) -> Tuple[str, Tuple[int, ...], str]:
    """Structured signature of one flattened argument leaf:
    ``(dtype, dims, spec)`` — the fields a jit cache key (and the
    persistent compile cache) actually specializes on.  Sharding spec
    renders only for NamedSharding (single-device default placements
    collapse to '-'); non-array leaves collapse to
    ``('py', (), repr(x))``.  THE one extraction behind both the
    rendered program key (:func:`program_key_of`, below) and the
    program-space auditor's dimension-level drift rule
    (``analysis/programspace.py`` imports this) — a signature change
    here changes both sides together, so they cannot drift."""
    aval = getattr(x, "aval", x)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return ("py", (), repr(x))
    spec = "-"
    sh = getattr(x, "sharding", None)
    if sh is not None and hasattr(sh, "spec"):
        spec = ",".join("None" if s is None else str(s)
                        for s in tuple(sh.spec))
        spec = spec or "-"
    return (str(dtype), tuple(int(d) for d in shape), spec)


def _leaf_sig(x) -> str:
    """``dtype[d0,d1,...]@spec`` rendering of :func:`leaf_struct`."""
    dtype, dims, spec = leaf_struct(x)
    if dtype == "py":
        return f"py:{spec}"
    return f"{dtype}[{','.join(str(d) for d in dims)}]@{spec}"


def program_key_of(name: str, args,
                   donate_argnums: Tuple[int, ...] = ()) -> str:
    """THE canonical compiled-program identity:
    ``slot|leaf sigs|donate=...``.  Computed by :class:`ObservedJit`
    at first compile (the ``program_key`` field of every ``compile``
    event) AND by the program-space auditor
    (``roc_tpu/analysis/programspace.py``) from the abstract avals —
    the same function on both sides is what makes static-vs-live
    program-set parity checkable at all.  Donated argnums are part of
    the key because donation changes the executable's aliasing (two
    otherwise-identical programs with different donation are distinct
    compiles)."""
    import jax
    leaves = jax.tree_util.tree_leaves(args)
    sig = ";".join(_leaf_sig(v) for v in leaves)
    don = ",".join(str(int(i)) for i in donate_argnums)
    return f"{name}|{sig}|donate={don}"


def cost_summary(compiled) -> Dict[str, Optional[float]]:
    """{'flops', 'bytes_accessed'} from ``cost_analysis()`` — which
    returns a list of per-computation dicts on jax<=0.4.x and a flat
    dict on newer releases; None fields when the backend (or an axon
    relay hop) does not implement it."""
    out: Dict[str, Optional[float]] = {"flops": None,
                                       "bytes_accessed": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            for key, field in (("flops", "flops"),
                               ("bytes accessed", "bytes_accessed")):
                v = ca.get(key)
                if v is not None and float(v) >= 0:
                    out[field] = float(v)
    except Exception:  # noqa: BLE001 - introspection is best-effort
        pass
    return out


def memory_summary(compiled) -> Dict[str, Optional[int]]:
    """Byte sizes from ``memory_analysis()`` (CompiledMemoryStats).
    ``peak_bytes`` approximates the executable's device footprint:
    arguments + outputs + temporaries, minus donated aliases."""
    out: Dict[str, Optional[int]] = {
        "peak_bytes": None, "argument_bytes": None,
        "output_bytes": None, "temp_bytes": None,
        "generated_code_bytes": None}
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return out
        parts = {}
        for field, attr in (("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("temp_bytes", "temp_size_in_bytes"),
                            ("generated_code_bytes",
                             "generated_code_size_in_bytes")):
            v = getattr(ma, attr, None)
            if v is not None:
                parts[field] = int(v)
                out[field] = int(v)
        alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
        if parts:
            out["peak_bytes"] = max(
                0, parts.get("argument_bytes", 0)
                + parts.get("output_bytes", 0)
                + parts.get("temp_bytes", 0) - alias)
    except Exception:  # noqa: BLE001 - introspection is best-effort
        pass
    return out


# Per-chip peak dense FLOP/s (bf16 MXU path — the precision the
# production configs run), keyed by device_kind substring.  MFU is a
# *style* of utilization number: a coarse, stable denominator for
# round-over-round comparison, not a vendor-exact ceiling.  CPU rigs
# have no entry — the mfu field is simply absent there.
PEAK_FLOPS_BY_KIND = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
    "v4": 275e12,
}


def peak_flops_per_s(device_kind: Optional[str] = None
                     ) -> Optional[float]:
    """Peak FLOP/s for ``device_kind`` (default: the current backend's
    first device); None when unknown — callers drop the MFU field
    rather than fabricate a denominator."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 - no backend, no MFU
            return None
    kind = (device_kind or "").lower()
    for key, val in PEAK_FLOPS_BY_KIND.items():
        if key in kind:
            return val
    return None


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "?"
    if n >= 1 << 28:
        return f"{n / 1024**3:.2f}GiB"
    if n >= 1 << 17:
        return f"{n / 1024**2:.1f}MiB"
    return f"{n / 1024:.1f}KiB"


class ObservedJit:
    """``jax.jit`` with first-compile telemetry.

    Drop-in for the trainer step slots: construct with the step
    *implementation* (it calls ``jax.jit`` itself) or with
    ``jitfn=`` for an already-wrapped callable (shard_map steps).
    ``modeled_bytes`` is the memory plan's estimate for this step;
    when XLA's actual peak exceeds it the event warns unconditionally.
    """

    # actual peak this far above the model warns even with verbose off
    # — both gates must trip: the ratio (the model missed a TERM, not
    # a rounding) and an absolute floor (at toy scale, fixed XLA
    # overheads dominate any estimate and the warning would be noise)
    UNDERSHOOT_WARN_RATIO = 1.1
    UNDERSHOOT_WARN_MIN_BYTES = 256 << 20

    def __init__(self, fn: Optional[Callable] = None, *,
                 name: str, jitfn: Optional[Callable] = None,
                 donate_argnums: Tuple[int, ...] = (),
                 modeled_bytes: Optional[int] = None,
                 verbose: bool = False):
        import jax
        if jitfn is None:
            jitfn = jax.jit(fn, donate_argnums=donate_argnums)
        self._jit = jitfn
        self.name = name
        # recorded for introspection (roc_tpu/analysis maps jaxpr
        # invars back to donated argnums); with jitfn= the caller
        # passes the argnums its own jax.jit was built with
        self.donate_argnums = donate_argnums
        self.modeled_bytes = modeled_bytes
        self.verbose = verbose
        self.cost: Optional[Dict[str, Any]] = None  # last compile event
        self._compiled = None
        self._degraded = False

    # expose the underlying jit's AOT surface for callers that poke it
    def lower(self, *args, **kw):
        return self._jit.lower(*args, **kw)

    def _observe(self, args) -> None:
        t0 = time.perf_counter()
        with Heartbeat(f"compile:{self.name}"):
            lowered = self._jit.lower(*args)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        fields: Dict[str, Any] = {
            "name": self.name,
            "lower_s": round(t1 - t0, 3),
            "compile_s": round(t2 - t1, 3),
            "modeled_bytes": self.modeled_bytes,
            # the canonical program identity — what the program-space
            # auditor's static enumeration is held against
            # (analysis/programspace.py parity check)
            "program_key": program_key_of(self.name, args,
                                          self.donate_argnums),
        }
        fields.update(cost_summary(compiled))
        fields.update(memory_summary(compiled))
        peak = fields.get("peak_bytes")
        undershoot = False
        if peak is not None and self.modeled_bytes:
            fields["model_delta_bytes"] = int(peak - self.modeled_bytes)
            fields["model_actual_ratio"] = round(
                peak / self.modeled_bytes, 3)
            undershoot = (
                peak > self.modeled_bytes * self.UNDERSHOOT_WARN_RATIO
                and peak - self.modeled_bytes
                > self.UNDERSHOOT_WARN_MIN_BYTES)
        flops = fields.get("flops")
        msg = (f"compile {self.name}: lower {fields['lower_s']}s + "
               f"compile {fields['compile_s']}s, "
               f"flops={flops:.3g} " if flops is not None else
               f"compile {self.name}: lower {fields['lower_s']}s + "
               f"compile {fields['compile_s']}s, flops=? ")
        msg += (f"peak={_fmt_bytes(peak)} "
                f"(modeled {_fmt_bytes(self.modeled_bytes)})")
        emit("compile", msg, console=self.verbose, **fields)
        if undershoot:
            emit("compile",
                 f"memory plan undershoots XLA actual for "
                 f"{self.name}: modeled "
                 f"{_fmt_bytes(self.modeled_bytes)} < actual "
                 f"{_fmt_bytes(peak)} "
                 f"({fields['model_actual_ratio']:.2f}x) — the "
                 f"autopilot's budget accounting is missing a term",
                 warning=True, name=self.name)
        self.cost = fields
        self._compiled = compiled

    def _degrade(self, e: BaseException):
        self._degraded = True
        self._compiled = None
        emit("compile",
             f"compile observer disabled for {self.name}: "
             f"{type(e).__name__}: {e}",
             console=self.verbose, name=self.name, degraded=True)

    def __call__(self, *args):
        if self._degraded:
            return self._jit(*args)
        if self._compiled is None:
            # ONLY the observation may degrade.  The executions below
            # stay outside the degrade path: their failures are the
            # step's own (and with donated args a retry through
            # self._jit could consume already-deleted buffers and mask
            # the real error).
            try:
                self._observe(args)
            except Exception as e:  # noqa: BLE001 - degrade, not die
                self._degrade(e)
                return self._jit(*args)
            return self._compiled(*args)
        try:
            # steady state: no per-step signature walk — the AOT
            # executable validates avals itself, far cheaper than a
            # host-side pytree compare in the very loop this observer
            # exists to measure
            return self._compiled(*args)
        except (TypeError, ValueError) as e:
            # aval/binding mismatch (new shapes/dtypes): raised before
            # any execution, args intact — re-observe once under the
            # new signature.  Device-side failures (JaxRuntimeError)
            # propagate untouched above.
            try:
                self._observe(args)
            except Exception:  # noqa: BLE001 - degrade on the ORIGINAL
                self._degrade(e)
                return self._jit(*args)
            return self._compiled(*args)
