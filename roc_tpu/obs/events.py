"""Categorized event bus: the ONE home for run diagnostics.

Every runtime decision the framework makes (impl auto-resolution,
memory plans, fuse counts, bdense occupancy) and everything the
hardware reports back (compile cost, epoch timing, stalls) flows
through :func:`emit` as a categorized event.  Two sinks:

- :class:`ConsoleSink` — preserves today's ``# ...`` stderr lines
  byte-for-byte (stdout stays a clean metrics stream; the lint
  ratchet ``scripts/lint_prints.sh`` enforces that).
- :class:`JsonlSink` — append-only structured JSONL, the machine-
  readable artifact ``python -m roc_tpu.report`` summarizes.

The module-level bus starts with a console sink only; a JSONL sink
attaches via :func:`configure` (the CLI's ``--events`` flag) or the
``ROC_TPU_EVENTS`` environment variable — inherited by bench child
processes, so a staged benchmark's events land in one artifact.

Deliberately jax-free and thread-safe: the stall heartbeat emits from
a watchdog thread while the main thread is blocked inside a fetch.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# Canonical categories (free-form strings are accepted — a new
# category must not require touching this module):
#   manifest  run-identity event emitted at trainer setup
#   resolve   config auto-resolution (impl/fuse/attention overrides)
#   plan      memory plans, bdense occupancy, partition/ring echoes
#   compile   lowering+compile cost, XLA cost/memory introspection
#   epoch     per-eval timing, phase spans, throughput
#   bench     benchmark stage lifecycle
#   stall     heartbeat "still waiting in <stage>" events
#   run       CLI lifecycle (resume, checkpoint, artifact writes)
#   analysis  roc-lint findings (python -m roc_tpu.analysis)
#   pipeline  streamed-tier / ring overlap telemetry (staging-pool
#             h2d_wait + overlap_frac, hop_compute vs hop_permute)
#   costmodel partition cost-model telemetry (core/costmodel.py):
#             split imbalance records, ridge observations, epoch-
#             boundary repartition decisions
#   programspace  compile-budget reports from the program-space
#             auditor (analysis/programspace.py): per-config program
#             counts, modeled compile cost, budget deltas
#   resilience  fault-tolerance lifecycle (roc_tpu/resilience):
#             injected faults, recovery retries, corrupt-checkpoint
#             fallbacks, preemption + emergency checkpoints, elastic
#             restores onto a different partition count
#   timeline  clock-sync handshakes and per-phase span batches the
#             cross-process trace merger consumes
#             (obs/timeline.py; python -m roc_tpu.timeline)
#   serve     inference-tier lifecycle (roc_tpu/serve): artifact
#             export/prewarm reports, server open/close summaries
#             (query/batch counts, latency percentiles), propagation-
#             table invalidations
#   sharding  replication-ledger / mesh-portability reports from the
#             sharding auditor (analysis/sharding_lint.py): per-rig
#             replicated bytes vs the ratcheted budget, full-width
#             sites, modeled per-device HBM per (parts, model) shape
#   checkpoint  checkpoint-v3 save lifecycle (utils/checkpoint.py +
#             resilience/async_save.py): committed async saves with
#             block/write/commit timings, superseded-snapshot drops,
#             sync-fallback decisions — the ``ckpt_*`` timeline spans
#             ride the ordinary timeline/spans batches
#   slo       SLO-engine transitions (obs/slo.py): dated burn-rate
#             breach/recovered events per objective, each carrying
#             the spec, burn multiple, alert window, and the windowed
#             value vs target — the breach also dumps the flight
#             recorder, and ``python -m roc_tpu.report --slo``
#             renders the breach windows from these records
#   protocol  protocol-audit surface from roc-lint level eight
#             (analysis/protocol_lint.py): the extracted wire
#             vocabulary per channel, transition-site index, and the
#             bounded model checker's per-model state counts and
#             invariant verdicts — ``python -m roc_tpu.report
#             --protocol`` renders the tables from these records
CATEGORIES = ("manifest", "resolve", "plan", "compile", "epoch",
              "bench", "stall", "run", "analysis", "pipeline",
              "costmodel", "programspace", "resilience", "timeline",
              "serve", "sharding", "checkpoint", "slo", "protocol")


# ---------------------------------------------------------- clock tuple
#
# Every event carries a ``(wall, monotonic, host, proc)`` clock tuple —
# ``t`` (epoch seconds, human-alignable but NTP-skewed), ``mono``
# (monotonic seconds, skew-free within a process but with an arbitrary
# per-process epoch), ``host``/``proc`` (the stream's identity).  The
# cross-process timeline merger (obs/timeline.py) aligns per-process
# monotonic clocks on the ``clock_sync`` handshake the trainers emit at
# the first-step barrier (train/trainer.py run_epoch_loop), so N
# per-process JSONL streams render on ONE time axis.  The bus stamps
# the tuple; call sites never hand-roll it (roc-lint ``event-clock``).

_HOST = socket.gethostname().split(".")[0]
_PROC: Optional[int] = None


def set_clock_identity(proc: Optional[int] = None,
                       host: Optional[str] = None) -> None:
    """Pin the process identity stamped on every event.  Called by the
    run manifest once jax knows ``process_index()``; before that the
    ``JAX_PROCESS_ID`` env var (or 0) serves."""
    global _PROC, _HOST
    if proc is not None:
        _PROC = int(proc)
    if host is not None:
        _HOST = host


def clock_identity() -> Dict[str, Any]:
    """The ``host``/``proc`` half of the clock tuple."""
    global _PROC
    if _PROC is None:
        try:
            _PROC = int(os.environ.get("JAX_PROCESS_ID", "0"))
        except ValueError:
            _PROC = 0
    return {"host": _HOST, "proc": _PROC}


def _jsonable(v: Any) -> Any:
    """Best-effort conversion to something json.dumps accepts."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set)):
        return [_jsonable(x) for x in v]
    try:
        import numpy as np
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.ndarray) and v.size <= 64:
            return v.tolist()
    except ImportError:  # numpy is always present in practice
        pass
    return str(v)


class ConsoleSink:
    """``# <message>`` lines on stderr — exactly the ad-hoc diagnostic
    format the event log replaces, so existing eyes and log scrapers
    keep working."""

    def __init__(self, stream=None):
        self._stream = stream

    def write(self, record: Dict[str, Any]) -> None:
        if not record.get("console", True):
            return
        stream = self._stream if self._stream is not None else sys.stderr
        print(f"# {record['msg']}", file=stream)

    def close(self) -> None:
        pass


class JsonlSink:
    """Append-only JSONL; the handle opens lazily on first event and
    every line is flushed (a timed-out run must still leave a readable
    artifact)."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def write(self, record: Dict[str, Any]) -> None:
        rec = {k: _jsonable(v) for k, v in record.items()
               if k != "console"}
        if self._fh is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a")
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class EventLog:
    """A bus fanning events out to its sinks.  Sink failures are
    swallowed after a one-time stderr note — telemetry must never take
    down the run it observes.

    Every record is stamped with the clock tuple (``t``/``mono``/
    ``host``/``proc``) and retained in a bounded ring buffer — the
    crash flight recorder :func:`dump_flight_record` writes on fatal
    paths, so a dead process's last seconds of telemetry survive even
    when no JSONL sink was configured."""

    def __init__(self, sinks: Optional[List] = None,
                 ring_events: Optional[int] = None):
        self.sinks: List = list(sinks) if sinks is not None else []
        self._lock = threading.Lock()
        self._sink_warned = False
        self.ring: collections.deque = collections.deque(
            maxlen=flight_ring_events() if ring_events is None
            else ring_events)

    def emit(self, cat: str, msg: str, console: bool = True,
             **fields: Any) -> Dict[str, Any]:
        record = {"t": round(time.time(), 3),
                  "mono": round(time.monotonic(), 6),
                  **clock_identity(),
                  "cat": cat, "msg": msg,
                  "console": console, **fields}
        with self._lock:
            self.ring.append(record)
            for sink in self.sinks:
                try:
                    # the bus lock IS the sink serializer: concurrent
                    # emitters writing the same JSONL handle unlocked
                    # would tear lines; the hold is bounded (one
                    # flushed line): roc-lint: ok=blocking-under-lock
                    sink.write(record)
                except Exception as e:  # noqa: BLE001 - never raise
                    if not self._sink_warned:
                        self._sink_warned = True
                        print(f"# event sink {type(sink).__name__} "
                              f"failed: {e!r} (further failures "
                              f"silent)", file=sys.stderr)
        return record

    def add_sink(self, sink) -> None:
        with self._lock:
            self.sinks.append(sink)

    def jsonl_path(self) -> Optional[str]:
        for sink in self.sinks:
            if isinstance(sink, JsonlSink):
                return sink.path
        return None

    def close(self) -> None:
        with self._lock:
            for sink in self.sinks:
                try:
                    sink.close()
                except Exception:  # noqa: BLE001
                    pass


_BUS: Optional[EventLog] = None
_BUS_LOCK = threading.Lock()


def get_bus() -> EventLog:
    """The process-global bus, created on first use: a console sink,
    plus a JSONL sink when ``ROC_TPU_EVENTS`` is set (bench children
    and multi-host workers inherit the artifact path via env)."""
    global _BUS
    with _BUS_LOCK:
        if _BUS is None:
            _BUS = EventLog([ConsoleSink()])
            env_path = os.environ.get("ROC_TPU_EVENTS")
            if env_path:
                _BUS.add_sink(JsonlSink(env_path))
        return _BUS


def configure(jsonl_path: Optional[str] = None,
              console: bool = True) -> EventLog:
    """(Re)build the global bus.  ``jsonl_path`` attaches the JSONL
    sink; ``console=False`` drops the stderr lines (library embedding
    that wants pure-JSONL telemetry)."""
    global _BUS
    with _BUS_LOCK:
        if _BUS is not None:
            _BUS.close()
        sinks: List = [ConsoleSink()] if console else []
        if jsonl_path:
            sinks.append(JsonlSink(jsonl_path))
        _BUS = EventLog(sinks)
        return _BUS


def emit(cat: str, msg: str, console: bool = True,
         **fields: Any) -> Dict[str, Any]:
    """Emit on the global bus.  ``console=False`` keeps an event out
    of the stderr stream (it still lands in the JSONL artifact) — the
    call-site analog of today's ``if config.verbose:`` gates."""
    return get_bus().emit(cat, msg, console=console, **fields)


# ------------------------------------------------ crash flight recorder
#
# The JSONL sink flushes per line, but a process that dies WITHOUT a
# sink configured — or whose interesting telemetry was console-only —
# takes its last seconds of events with it (the r01-r05 probes died
# exactly like that).  The bus therefore keeps a bounded ring of recent
# records, and the fatal paths (preemption guard, stall watchdog,
# fault-injection sites about to SIGKILL, the unhandled-exception hook)
# dump it to a dated ``flightrecord_*.json`` for the post-mortem.

# ring capacity (events, not bytes): ~30 s of a chatty run
FLIGHT_RING_EVENTS = 256


def flight_ring_events() -> int:
    try:
        return int(os.environ.get("ROC_TPU_FLIGHT_EVENTS",
                                  FLIGHT_RING_EVENTS))
    except ValueError:
        return FLIGHT_RING_EVENTS


def flight_record_dir() -> str:
    """Where dumps land: ``ROC_TPU_FLIGHT_DIR``, else next to the JSONL
    events artifact, else the cwd."""
    env = os.environ.get("ROC_TPU_FLIGHT_DIR")
    if env:
        return env
    jl = get_bus().jsonl_path()
    if jl:
        return os.path.dirname(os.path.abspath(jl)) or "."
    return "."


def dump_flight_record(reason: str,
                       path: Optional[str] = None) -> Optional[str]:
    """Write the ring buffer to a dated flight-record JSON; returns the
    path, or None on failure (a dump must never mask the failure that
    triggered it).  Filename carries the date, pid, and a slug of the
    reason so multiple dumps of one incident coexist."""
    bus = get_bus()
    try:
        ident = clock_identity()
        if path is None:
            slug = "".join(c if c.isalnum() else "-"
                           for c in reason)[:40].strip("-")
            name = (f"flightrecord_"
                    f"{time.strftime('%Y%m%d-%H%M%S')}_"
                    f"p{ident['proc']}_pid{os.getpid()}_{slug}.json")
            path = os.path.join(flight_record_dir(), name)
        with bus._lock:
            events = [
                {k: _jsonable(v) for k, v in r.items() if k != "console"}
                for r in bus.ring]
        payload = {"reason": reason,
                   "t": round(time.time(), 3),
                   "mono": round(time.monotonic(), 6),
                   "pid": os.getpid(), **ident,
                   "n_events": len(events), "events": events}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except Exception as e:  # noqa: BLE001 - never mask the trigger
        try:
            print(f"# flight-record dump failed: {e!r}",
                  file=sys.stderr)
        except OSError:
            pass
        return None
    try:
        print(f"# flight record ({reason}): {path}", file=sys.stderr)
    except OSError:
        pass
    return path


_EXCEPTHOOK_INSTALLED = False


def install_excepthook() -> None:
    """Chain a flight-record dump onto ``sys.excepthook`` so an
    unhandled exception leaves the last telemetry window behind.
    Idempotent; the previous hook always runs."""
    global _EXCEPTHOOK_INSTALLED
    if _EXCEPTHOOK_INSTALLED:
        return
    _EXCEPTHOOK_INSTALLED = True
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
            dump_flight_record(f"unhandled {exc_type.__name__}")
        prev(exc_type, exc, tb)

    sys.excepthook = hook
