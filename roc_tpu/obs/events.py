"""Categorized event bus: the ONE home for run diagnostics.

Every runtime decision the framework makes (impl auto-resolution,
memory plans, fuse counts, bdense occupancy) and everything the
hardware reports back (compile cost, epoch timing, stalls) flows
through :func:`emit` as a categorized event.  Two sinks:

- :class:`ConsoleSink` — preserves today's ``# ...`` stderr lines
  byte-for-byte (stdout stays a clean metrics stream; the lint
  ratchet ``scripts/lint_prints.sh`` enforces that).
- :class:`JsonlSink` — append-only structured JSONL, the machine-
  readable artifact ``python -m roc_tpu.report`` summarizes.

The module-level bus starts with a console sink only; a JSONL sink
attaches via :func:`configure` (the CLI's ``--events`` flag) or the
``ROC_TPU_EVENTS`` environment variable — inherited by bench child
processes, so a staged benchmark's events land in one artifact.

Deliberately jax-free and thread-safe: the stall heartbeat emits from
a watchdog thread while the main thread is blocked inside a fetch.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# Canonical categories (free-form strings are accepted — a new
# category must not require touching this module):
#   manifest  run-identity event emitted at trainer setup
#   resolve   config auto-resolution (impl/fuse/attention overrides)
#   plan      memory plans, bdense occupancy, partition/ring echoes
#   compile   lowering+compile cost, XLA cost/memory introspection
#   epoch     per-eval timing, phase spans, throughput
#   bench     benchmark stage lifecycle
#   stall     heartbeat "still waiting in <stage>" events
#   run       CLI lifecycle (resume, checkpoint, artifact writes)
#   analysis  roc-lint findings (python -m roc_tpu.analysis)
#   pipeline  streamed-tier / ring overlap telemetry (staging-pool
#             h2d_wait + overlap_frac, hop_compute vs hop_permute)
#   costmodel partition cost-model telemetry (core/costmodel.py):
#             split imbalance records, ridge observations, epoch-
#             boundary repartition decisions
#   programspace  compile-budget reports from the program-space
#             auditor (analysis/programspace.py): per-config program
#             counts, modeled compile cost, budget deltas
#   resilience  fault-tolerance lifecycle (roc_tpu/resilience):
#             injected faults, recovery retries, corrupt-checkpoint
#             fallbacks, preemption + emergency checkpoints, elastic
#             restores onto a different partition count
CATEGORIES = ("manifest", "resolve", "plan", "compile", "epoch",
              "bench", "stall", "run", "analysis", "pipeline",
              "costmodel", "programspace", "resilience")


def _jsonable(v: Any) -> Any:
    """Best-effort conversion to something json.dumps accepts."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set)):
        return [_jsonable(x) for x in v]
    try:
        import numpy as np
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.ndarray) and v.size <= 64:
            return v.tolist()
    except ImportError:  # numpy is always present in practice
        pass
    return str(v)


class ConsoleSink:
    """``# <message>`` lines on stderr — exactly the ad-hoc diagnostic
    format the event log replaces, so existing eyes and log scrapers
    keep working."""

    def __init__(self, stream=None):
        self._stream = stream

    def write(self, record: Dict[str, Any]) -> None:
        if not record.get("console", True):
            return
        stream = self._stream if self._stream is not None else sys.stderr
        print(f"# {record['msg']}", file=stream)

    def close(self) -> None:
        pass


class JsonlSink:
    """Append-only JSONL; the handle opens lazily on first event and
    every line is flushed (a timed-out run must still leave a readable
    artifact)."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def write(self, record: Dict[str, Any]) -> None:
        rec = {k: _jsonable(v) for k, v in record.items()
               if k != "console"}
        if self._fh is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a")
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class EventLog:
    """A bus fanning events out to its sinks.  Sink failures are
    swallowed after a one-time stderr note — telemetry must never take
    down the run it observes."""

    def __init__(self, sinks: Optional[List] = None):
        self.sinks: List = list(sinks) if sinks is not None else []
        self._lock = threading.Lock()
        self._sink_warned = False

    def emit(self, cat: str, msg: str, console: bool = True,
             **fields: Any) -> Dict[str, Any]:
        record = {"t": round(time.time(), 3), "cat": cat, "msg": msg,
                  "console": console, **fields}
        with self._lock:
            for sink in self.sinks:
                try:
                    sink.write(record)
                except Exception as e:  # noqa: BLE001 - never raise
                    if not self._sink_warned:
                        self._sink_warned = True
                        print(f"# event sink {type(sink).__name__} "
                              f"failed: {e!r} (further failures "
                              f"silent)", file=sys.stderr)
        return record

    def add_sink(self, sink) -> None:
        with self._lock:
            self.sinks.append(sink)

    def jsonl_path(self) -> Optional[str]:
        for sink in self.sinks:
            if isinstance(sink, JsonlSink):
                return sink.path
        return None

    def close(self) -> None:
        with self._lock:
            for sink in self.sinks:
                try:
                    sink.close()
                except Exception:  # noqa: BLE001
                    pass


_BUS: Optional[EventLog] = None
_BUS_LOCK = threading.Lock()


def get_bus() -> EventLog:
    """The process-global bus, created on first use: a console sink,
    plus a JSONL sink when ``ROC_TPU_EVENTS`` is set (bench children
    and multi-host workers inherit the artifact path via env)."""
    global _BUS
    with _BUS_LOCK:
        if _BUS is None:
            _BUS = EventLog([ConsoleSink()])
            env_path = os.environ.get("ROC_TPU_EVENTS")
            if env_path:
                _BUS.add_sink(JsonlSink(env_path))
        return _BUS


def configure(jsonl_path: Optional[str] = None,
              console: bool = True) -> EventLog:
    """(Re)build the global bus.  ``jsonl_path`` attaches the JSONL
    sink; ``console=False`` drops the stderr lines (library embedding
    that wants pure-JSONL telemetry)."""
    global _BUS
    with _BUS_LOCK:
        if _BUS is not None:
            _BUS.close()
        sinks: List = [ConsoleSink()] if console else []
        if jsonl_path:
            sinks.append(JsonlSink(jsonl_path))
        _BUS = EventLog(sinks)
        return _BUS


def emit(cat: str, msg: str, console: bool = True,
         **fields: Any) -> Dict[str, Any]:
    """Emit on the global bus.  ``console=False`` keeps an event out
    of the stderr stream (it still lands in the JSONL artifact) — the
    call-site analog of today's ``if config.verbose:`` gates."""
    return get_bus().emit(cat, msg, console=console, **fields)
