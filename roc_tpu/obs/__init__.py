"""Structured run telemetry (the observability layer).

The reference ships only Legion log categories and commented-out
``Realm::Clock`` micro-timers (SURVEY.md §5: a gap to fill, not copy).
This package makes every run self-describing:

- :mod:`events` — categorized event bus with a JSONL sink and a
  console sink that preserves the ``# ...`` stderr diagnostic lines.
- :mod:`manifest` — the run-manifest event (config, jax version,
  device topology, resolved impl/fuse/halo, git sha) emitted at
  trainer setup.
- :mod:`compile_watch` — jit wrapper capturing lowering/compile wall
  time plus the compiled executable's ``cost_analysis()`` /
  ``memory_analysis()``, and the delta between XLA's actual peak and
  the memory plan's modeled budget.
- :mod:`heartbeat` — stall watchdog emitting periodic "still waiting
  in <stage>" events so a hang is diagnosed instead of a blank
  timeout.
- :mod:`metrics_registry` — the *live* layer: counters, gauges, and
  sliding-window log-bucket quantile histograms the serving tier and
  trainer record into (windowed shed/error/availability rates,
  current p50/p95/p99).
- :mod:`slo` — declarative objectives with multi-window burn-rate
  alerting over the registry; breaches emit dated ``slo`` events and
  dump the flight recorder.

``python -m roc_tpu.report`` summarizes the emitted JSONL
(``--slo`` renders a registry snapshot + SLO verdict dashboard).
"""

from .events import (CATEGORIES, ConsoleSink, EventLog,  # noqa: F401
                     JsonlSink, clock_identity, configure,
                     dump_flight_record, emit, get_bus,
                     install_excepthook, set_clock_identity)
from .heartbeat import Heartbeat  # noqa: F401
from .manifest import run_manifest  # noqa: F401
from .metrics_registry import (Counter, Gauge,  # noqa: F401
                               Histogram, MetricsRegistry)
from .slo import Slo, SloEngine, parse_slo  # noqa: F401
