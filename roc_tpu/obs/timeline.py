"""Unified distributed timeline: merge N per-process event/metrics
JSONL streams into ONE Chrome-trace/Perfetto JSON.

Under lockstep SPMD each process writes its own ``--events`` JSONL
with no shared clock; post-mortems so far re-read N files side by
side and guessed at alignment.  This module is the other half of the
clock tuple (obs/events.py): every record carries ``(t, mono, host,
proc)``, the trainers emit a ``clock_sync`` handshake at the
first-step barrier (train/trainer.py run_epoch_loop — every process
crosses that collective within one step of each other), and the
merger aligns each process's monotonic clock on its sync point, so
the merged trace renders on one time axis regardless of NTP skew.

Output is the Chrome trace-event format Perfetto/chrome://tracing
load directly:

- one *process* lane per ``(host, proc)`` stream, named
  ``proc<p>@<host>``;
- a ``phases`` thread per lane with the span laps (compile / train /
  eval / head_forward / tail_grad / head_wgrad / update) the trainers
  flush as ``timeline``-category span batches;
- an ``h2d`` thread with the StagingPool per-block wait/stage spans;
- a ``markers`` thread with instant events for stall heartbeats,
  resilience faults/recoveries/preemptions, rebalance decisions, and
  the per-epoch straggler attribution records (``costmodel`` events,
  kind=straggler — the same record the partition cost model's ridge
  observation consumes).

Like ``roc_tpu/report.py`` this is a *reader*: artifacts from dead
runs are fine, nothing here touches a backend, and the module is
deliberately stdlib-only (``python roc_tpu/obs/timeline.py`` works on
a box without jax; ``python -m roc_tpu.timeline`` is the packaged
entry point).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# thread (tid) layout inside each process lane
TID_PHASES = 0
TID_H2D = 1
TID_MARKERS = 2
_TID_NAMES = {TID_PHASES: "phases", TID_H2D: "h2d",
              TID_MARKERS: "markers"}


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Tolerant JSONL reader (same contract as roc_tpu/report.py: a
    run killed mid-write leaves at most one torn tail line)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def expand_paths(patterns: List[str]) -> List[str]:
    """Literal paths plus glob patterns, deduped, order-preserving —
    ``roc_tpu.timeline ev_p*.jsonl`` merges a whole rig's streams.
    A named-but-missing path (or a glob with zero matches) is KEPT so
    the caller's ``open()`` fails loudly: a merge that silently drops
    the dead process's stream is exactly the wrong post-mortem."""
    out: List[str] = []
    for p in patterns:
        hits = [p] if os.path.exists(p) else sorted(_glob.glob(p))
        for h in (hits or [p]):
            if h not in out:
                out.append(h)
    return out


def _proc_key(rec: Dict[str, Any]) -> Tuple[str, int]:
    """The stream identity half of the clock tuple; legacy records
    without it collapse into one lane."""
    try:
        proc = int(rec.get("proc", 0) or 0)
    except (TypeError, ValueError):
        proc = 0
    return (str(rec.get("host", "?")), proc)


def _median(vals: List[float]) -> float:
    vs = sorted(vals)
    n = len(vs)
    return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])


def clock_offsets(events: List[Dict[str, Any]]
                  ) -> Dict[Tuple[str, int], Optional[float]]:
    """Per-process ``offset`` such that ``offset + mono`` places a
    record on the merged wall axis.

    Preferred anchor: the ``clock_sync`` handshake (all processes
    cross the first-step barrier near-simultaneously, so their sync
    points are pinned to the MEDIAN sync wall time — monotonic clocks
    then agree to barrier skew, not NTP skew).  Streams without a
    handshake fall back to wall-aligning their first stamped record;
    streams with no ``mono`` at all get None (their ``t`` is used
    directly)."""
    keys = {k: None for k in (_proc_key(r) for r in events)}
    syncs: Dict[Tuple[str, int], Dict[str, Any]] = {}
    firsts: Dict[Tuple[str, int], Dict[str, Any]] = {}
    for r in events:
        if r.get("t") is None or r.get("mono") is None:
            continue
        k = _proc_key(r)
        firsts.setdefault(k, r)
        if (r.get("cat") == "timeline"
                and r.get("kind") == "clock_sync" and k not in syncs):
            syncs[k] = r
    offsets: Dict[Tuple[str, int], Optional[float]] = dict(keys)
    ref_wall = (_median([float(s["t"]) for s in syncs.values()])
                if syncs else None)
    for k in offsets:
        if k in syncs and ref_wall is not None:
            offsets[k] = ref_wall - float(syncs[k]["mono"])
        elif k in firsts:
            r = firsts[k]
            offsets[k] = float(r["t"]) - float(r["mono"])
    return offsets


def _ts_s(rec: Dict[str, Any],
          offset: Optional[float]) -> Optional[float]:
    """A record's position on the merged wall axis (seconds)."""
    mono = rec.get("mono")
    if mono is not None and offset is not None:
        return offset + float(mono)
    t = rec.get("t")
    return float(t) if t is not None else None


def straggler_records(events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """The per-epoch straggler attribution table: one row per
    ``costmodel`` straggler event — which shard was (predicted)
    slowest for each measured lap, by how much over the mean."""
    out = []
    for r in events:
        if r.get("cat") == "costmodel" and r.get("kind") == "straggler":
            out.append({"epoch": r.get("epoch"),
                        "part": r.get("straggler_part"),
                        "ratio": r.get("straggler_ratio"),
                        "measured_ms": r.get("measured_ms"),
                        "proc": r.get("proc"),
                        "num_parts": r.get("num_parts")})
    out.sort(key=lambda d: (d["epoch"] is None, d["epoch"]))
    return out


def _marker(rec: Dict[str, Any]) -> Optional[Tuple[str, Dict[str, Any]]]:
    """(name, args) for records rendered as instant markers; None for
    records the merger represents some other way (or not at all)."""
    cat = rec.get("cat")
    if cat == "stall":
        return (f"stall:{rec.get('stage')}",
                {"elapsed_s": rec.get("elapsed_s"),
                 "beat": rec.get("beat")})
    if cat == "resilience":
        kind = rec.get("kind", "resilience")
        site = rec.get("site")
        return (f"{kind}:{site}" if site else str(kind),
                {"msg": rec.get("msg"), "epoch": rec.get("epoch")})
    if cat == "costmodel":
        if rec.get("kind") == "straggler":
            return (f"straggler:part{rec.get('straggler_part')}",
                    {"epoch": rec.get("epoch"),
                     "ratio": rec.get("straggler_ratio"),
                     "measured_ms": rec.get("measured_ms"),
                     "predicted_cost": rec.get("predicted_cost")})
        if "rebalance" in rec or "gain" in rec:
            return ("rebalance", {"msg": rec.get("msg"),
                                  "gain": rec.get("gain"),
                                  "recompile": rec.get("recompile")})
        return None
    if cat == "timeline" and rec.get("kind") == "clock_sync":
        return ("clock_sync", {"epoch": rec.get("epoch")})
    if cat == "serve":
        # server lifecycle markers on the serving process's lane (the
        # per-microbatch spans ride the ordinary span batches); router
        # failover/hedge markers carry the replica index so a killed
        # replica's failover is findable on the timeline (ISSUE 13
        # acceptance), and the request id(s) so the marker joins the
        # per-request distributed trace (PR 17 --request)
        return (f"serve:{rec.get('kind', 'serve')}",
                {"msg": rec.get("msg"),
                 "n_queries": rec.get("n_queries"),
                 "rows": rec.get("rows"),
                 "replica": rec.get("replica"),
                 "requeued": rec.get("requeued"),
                 "version": rec.get("version"),
                 "rid": rec.get("rid"),
                 "rids": rec.get("rids") or None})
    if cat == "slo":
        # SLO breach/recovery transitions render as markers on the
        # emitting component's lane
        return (f"slo:{rec.get('kind', 'slo')}:{rec.get('slo')}",
                {"msg": rec.get("msg"), "spec": rec.get("spec"),
                 "burn": rec.get("burn"), "value": rec.get("value"),
                 "target": rec.get("target")})
    if cat in ("bench", "programspace", "run"):
        return (f"{cat}", {"msg": rec.get("msg")})
    return None


def merge_timeline(events: List[Dict[str, Any]],
                   metrics: Optional[List[Dict[str, Any]]] = None
                   ) -> Dict[str, Any]:
    """Merge already-loaded records into the Chrome-trace object.
    ``events`` may concatenate any number of per-process streams (the
    clock tuple identifies each record's lane); ``metrics`` records
    contribute per-eval epoch markers."""
    metrics = metrics or []
    offsets = clock_offsets(events + metrics)
    keys = sorted(offsets)
    pid_of = {k: i + 1 for i, k in enumerate(keys)}

    trace: List[Dict[str, Any]] = []
    for k in keys:
        pid = pid_of[k]
        trace.append({"ph": "M", "name": "process_name", "pid": pid,
                      "args": {"name": f"proc{k[1]}@{k[0]}"}})
        trace.append({"ph": "M", "name": "process_sort_index",
                      "pid": pid, "args": {"sort_index": k[1]}})
        for tid, tname in _TID_NAMES.items():
            trace.append({"ph": "M", "name": "thread_name", "pid": pid,
                          "tid": tid, "args": {"name": tname}})

    spans: List[Tuple[float, float, str, int, int, Dict[str, Any]]] = []
    instants: List[Tuple[float, str, int, int, Dict[str, Any]]] = []
    for rec in events:
        k = _proc_key(rec)
        off = offsets.get(k)
        pid = pid_of[k]
        ts = _ts_s(rec, off)
        if rec.get("cat") == "timeline" and rec.get("kind") == "spans":
            if off is None:
                continue    # mono-anchored batch with no alignment
            for lap in rec.get("spans") or []:
                try:
                    name, t0, ms = lap[0], float(lap[1]), float(lap[2])
                except (TypeError, ValueError, IndexError):
                    continue
                # optional 4th element: per-span args (the serving
                # tier stamps rids/batch/version there — PR 17 request
                # tracing); older 3-element laps merge unchanged
                args = (lap[3] if len(lap) > 3
                        and isinstance(lap[3], dict) else {})
                tid = (TID_H2D if str(name).startswith("h2d")
                       else TID_PHASES)
                spans.append((off + t0, ms, str(name), pid, tid, args))
            continue
        if ts is None:
            continue
        if rec.get("cat") == "compile" and "lower_s" in rec:
            dur_ms = (float(rec.get("lower_s") or 0)
                      + float(rec.get("compile_s") or 0)) * 1e3
            spans.append((ts - dur_ms / 1e3, dur_ms,
                          f"compile:{rec.get('name')}", pid,
                          TID_PHASES,
                          {"flops": rec.get("flops"),
                           "peak_bytes": rec.get("peak_bytes"),
                           "program_key": rec.get("program_key")}))
            continue
        mk = _marker(rec)
        if mk is not None:
            name, args = mk
            instants.append((ts, name, pid, TID_MARKERS, args))
    for rec in metrics:
        if rec.get("epoch") is None:
            continue
        ts = _ts_s(rec, offsets.get(_proc_key(rec)))
        if ts is None:
            continue
        args = {f: rec.get(f) for f in
                ("epoch_ms", "eval_ms", "train_loss", "overlap_frac",
                 "straggler_part", "straggler_ratio")
                if rec.get(f) is not None}
        instants.append((ts, f"epoch {int(rec['epoch'])}",
                         pid_of[_proc_key(rec)], TID_MARKERS, args))

    all_ts = [s[0] for s in spans] + [i[0] for i in instants]
    base = min(all_ts) if all_ts else 0.0
    for t0, ms, name, pid, tid, args in sorted(
            spans, key=lambda s: s[0]):
        trace.append({"ph": "X", "name": name, "cat": "span",
                      "ts": round((t0 - base) * 1e6, 1),
                      "dur": max(round(ms * 1e3, 1), 1.0),
                      "pid": pid, "tid": tid,
                      "args": {kk: v for kk, v in args.items()
                               if v is not None}})
    for ts, name, pid, tid, args in sorted(
            instants, key=lambda s: s[0]):
        trace.append({"ph": "i", "s": "t", "name": name,
                      "cat": "marker",
                      "ts": round((ts - base) * 1e6, 1),
                      "pid": pid, "tid": tid,
                      "args": {kk: v for kk, v in args.items()
                               if v is not None}})

    return {
        "displayTimeUnit": "ms",
        "traceEvents": trace,
        # non-standard top-level keys are preserved by Perfetto and
        # give the merged artifact a machine-readable summary
        "roc_tpu": {
            "processes": [{"pid": pid_of[k], "host": k[0],
                           "proc": k[1],
                           "aligned": offsets[k] is not None}
                          for k in keys],
            "base_wall_s": round(base, 3),
            "straggler": straggler_records(events),
        },
    }


def request_trace(doc: Dict[str, Any], rid: str) -> Dict[str, Any]:
    """One request's distributed trace, pulled from a merged doc: the
    router's ``route_request`` span, every replica microbatch span
    whose ``rids`` include it, and the hedge/failover markers carrying
    it — across however many process lanes the request touched.
    ``connected`` verifies the trace is ONE story: a router span
    exists and every other event overlaps it (small slack for
    clock-sync skew) — a hedged or failover-requeued request must
    still merge into a single connected trace, not orphaned
    fragments."""
    evs = []
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i"):
            continue
        args = ev.get("args") or {}
        rids = args.get("rids")
        if args.get("rid") == rid or (
                isinstance(rids, list) and rid in rids):
            evs.append(ev)
    evs.sort(key=lambda e: e.get("ts", 0.0))
    lanes = sorted({e["pid"] for e in evs})
    routes = [e for e in evs if e.get("ph") == "X"
              and str(e.get("name", "")).startswith("route_request")]
    connected = bool(routes)
    slack_us = 50e3
    for r in routes:
        lo = r["ts"] - slack_us
        hi = r["ts"] + r.get("dur", 0.0) + slack_us
        for e in evs:
            if e is r:
                continue
            if not (lo <= e["ts"] <= hi):
                connected = False
    t0 = min((e["ts"] for e in evs), default=0.0)
    t1 = max((e["ts"] + e.get("dur", 0.0) for e in evs), default=0.0)
    return {"rid": rid,
            "n_events": len(evs),
            "lanes": lanes,
            "connected": connected,
            "span_ms": round((t1 - t0) / 1e3, 3),
            "events": [{"name": e.get("name"),
                        "ph": e.get("ph"),
                        "pid": e.get("pid"), "tid": e.get("tid"),
                        "ts_ms": round(e.get("ts", 0.0) / 1e3, 3),
                        "dur_ms": (round(e["dur"] / 1e3, 3)
                                   if e.get("dur") is not None
                                   else None),
                        "args": e.get("args") or {}}
                       for e in evs]}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="roc_tpu.timeline", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("events", nargs="+",
                    help="per-process event JSONL files (globs ok, "
                         "e.g. 'run_ev_p*.jsonl')")
    ap.add_argument("--metrics", action="append", default=[],
                    help="per-process metrics JSONL (repeatable; "
                         "globs ok)")
    ap.add_argument("-o", "--out", default="timeline_trace.json",
                    help="merged Chrome-trace/Perfetto JSON output "
                         "(default: %(default)s)")
    ap.add_argument("--request", default=None, metavar="RID",
                    help="also print the distributed trace of ONE "
                         "request id (router span, replica microbatch "
                         "spans, hedge/failover markers)")
    args = ap.parse_args(argv)

    ev_paths = expand_paths(args.events)
    if not ev_paths:
        print(f"error: no event files match {args.events}",
              file=sys.stderr)
        return 2
    events: List[Dict[str, Any]] = []
    for p in ev_paths:
        try:
            events.extend(load_jsonl(p))
        except OSError as e:
            print(f"error: cannot read {p}: {e}", file=sys.stderr)
            return 2
    metrics: List[Dict[str, Any]] = []
    for p in expand_paths(args.metrics):
        try:
            metrics.extend(load_jsonl(p))
        except OSError as e:
            print(f"error: cannot read {p}: {e}", file=sys.stderr)
            return 2

    doc = merge_timeline(events, metrics)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    meta = doc["roc_tpu"]
    summary = {
        "out": args.out,
        "streams": len(ev_paths),
        "processes": len(meta["processes"]),
        "lanes": [p_["pid"] for p_ in meta["processes"]],
        "events": len(doc["traceEvents"]),
        "straggler": meta["straggler"][-8:],
    }
    if args.request:
        summary["request"] = request_trace(doc, args.request)
    # one machine-readable line: this CLI's stdout IS its product
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
