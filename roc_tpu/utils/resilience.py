"""Failure detection + elastic recovery.

The reference has **none** of this: every error path is an ``assert``
or ``exit(1)`` (``cuda_helper.h:6-28``, ``nccl_helper.h:6-13``) and a
killed run loses all progress since weights are never saved (SURVEY §5
lists both as gaps to fill).  The TPU-idiomatic recovery model is
checkpoint-restart:

- :func:`check_finite` — numeric failure detection: masked-loss
  NaN/Inf is the one silent failure mode of this workload (the XLA
  runtime turns everything else into a raised exception).
- :class:`CheckpointRotation` — keep-last-k atomic checkpoints.
- :func:`train_with_recovery` — drives ``trainer.train()`` in
  checkpointed rounds; on a numeric failure or crash it restores the
  most recent good checkpoint and retries (bounded), resuming the
  epoch counter / lr schedule / PRNG key exactly where the checkpoint
  left them.
"""

from __future__ import annotations

import math
import os
from typing import Callable, Dict, List, Optional

from .checkpoint import checkpoint_trainer, restore_trainer


class NumericFailure(RuntimeError):
    """Raised when training metrics go NaN/Inf."""


def check_finite(metrics: Dict[str, float]) -> None:
    loss = metrics.get("train_loss")
    if loss is not None and not math.isfinite(loss):
        raise NumericFailure(f"non-finite train loss: {loss!r} "
                             f"at epoch {metrics.get('epoch')}")


def check_params_finite(params) -> None:
    """Raise if any parameter leaf holds NaN/Inf (guards checkpoints
    against persisting a poisoned state)."""
    import jax
    import jax.numpy as jnp
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        if not bool(jnp.isfinite(leaf).all()):
            raise NumericFailure(
                f"non-finite parameter at {jax.tree_util.keystr(path)}")


class CheckpointRotation:
    """Keep the most recent ``keep`` checkpoints of a trainer as
    ``<prefix>.<epoch>.npz`` (saves are atomic via checkpoint.py)."""

    def __init__(self, prefix: str, keep: int = 3):
        self.prefix = prefix
        self.keep = keep

    def path(self, epoch: int) -> str:
        return f"{self.prefix}.{epoch}.npz"

    def existing(self) -> List[int]:
        d = os.path.dirname(self.prefix) or "."
        base = os.path.basename(self.prefix)
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            if name.startswith(base + ".") and name.endswith(".npz"):
                mid = name[len(base) + 1:-4]
                if mid.isdigit():
                    out.append(int(mid))
        return sorted(out)

    def save(self, trainer) -> str:
        p = self.path(trainer.epoch)
        checkpoint_trainer(trainer, p)
        for old in self.existing()[:-self.keep]:
            try:
                os.remove(self.path(old))
            except OSError:
                pass
        return p

    def restore_latest(self, trainer,
                       only_if_ahead: bool = False) -> Optional[int]:
        """Restore the newest checkpoint into ``trainer``; returns its
        epoch or None if there is none.  ``only_if_ahead`` skips the
        restore when the trainer has already progressed past the newest
        checkpoint (never rewind live progress)."""
        epochs = self.existing()
        if not epochs:
            return None
        if only_if_ahead and epochs[-1] <= trainer.epoch:
            return None
        restore_trainer(trainer, self.path(epochs[-1]))
        return epochs[-1]


def train_with_recovery(trainer, target_epoch: int,
                        rotation: CheckpointRotation,
                        checkpoint_every: int = 50,
                        max_retries: int = 3,
                        on_failure: Optional[Callable[[Exception], None]]
                        = None) -> List[Dict[str, float]]:
    """Train until ``trainer.epoch == target_epoch`` in checkpointed
    rounds, with bounded retry-from-last-good-checkpoint on numeric
    failure.

    Resumes from the newest existing checkpoint first, so re-invoking
    the same command after a crash continues the run (elastic
    restart).  On retry the trainer's PRNG key is perturbed — an
    identical key would deterministically replay the same failing
    trajectory (dropout masks included).
    """
    import jax
    history: List[Dict[str, float]] = []
    # resume a crashed run, but never rewind a live trainer that is
    # already past the newest checkpoint
    rotation.restore_latest(trainer, only_if_ahead=True)
    retries = 0
    while trainer.epoch < target_epoch:
        round_epochs = min(checkpoint_every, target_epoch - trainer.epoch)
        try:
            hist = trainer.train(epochs=round_epochs)
            for m in hist:
                check_finite(m)
            # metrics only exist on eval epochs; a NaN can arise
            # between the round's last eval and the round boundary, so
            # validate the params themselves before persisting
            check_params_finite(trainer.params)
            history.extend(hist)
            rotation.save(trainer)
            retries = 0
        except NumericFailure as e:
            if on_failure:
                on_failure(e)
            retries += 1
            if retries > max_retries:
                raise
            if rotation.restore_latest(trainer) is None:
                raise
            trainer.key = jax.random.fold_in(trainer.key, retries)
    return history
