"""Back-compat shim: the recovery machinery grew into the
:mod:`roc_tpu.resilience` subsystem (rotation + retry loop in
``resilience/recovery.py``, preemption in ``resilience/preempt.py``,
fault injection in ``resilience/inject.py``).  Import from there; this
module re-exports the original surface so existing callers keep
working."""

from __future__ import annotations

from ..obs.heartbeat import StallFailure  # noqa: F401
from ..resilience.preempt import Preempted, RESTARTABLE_EXIT_CODE  # noqa: F401
from ..resilience.recovery import (  # noqa: F401
    RECOVERABLE, CheckpointRotation, NumericFailure, check_finite,
    check_params_finite, train_with_recovery)
