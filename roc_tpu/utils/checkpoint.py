"""Checkpoint / resume with integrity + identity validation.

The reference has **no** persistence at all (SURVEY §5: weights are
never saved; the only cache is the feature-CSV binary).  This module
grew through three formats:

- **v1** — a bare ``.npz`` of the flattened state (no validation).
- **v2** (resilience PR) — one atomic ``.npz`` with a JSON
  ``__header__`` carrying per-array CRC32s and a two-half config
  fingerprint.  Exactly right while params/opt state are fully
  replicated — and wrong the moment the 2-D ``(parts, model)`` mesh
  shards parameters: one process cannot (and must not) serialize
  arrays it only holds a shard of.
- **v3** (this PR) — a checkpoint is a DIRECTORY:

  .. code-block:: text

      <path>/                      (e.g. ck.40/)
        shard_00000.npz            per-PROCESS shard file: only the
        shard_00001.npz            array pieces this process owns
        MANIFEST.json              the commit record (process 0 only)

  Each process writes only the shards it owns (``replica_id == 0``
  dedup over the array's global sharding — a fully replicated array
  is owned by process 0 alone, which is the degenerate
  sharded→replicated path today's 1-D mesh exercises).  Every shard
  member carries the PR-14 sharding-spec vocabulary in the shard
  header (global shape, per-dim mesh-axis spec, piece index ranges),
  so restore can gather ANY saved (P, mesh) layout onto any restore
  layout: the loader reassembles full host arrays from the recorded
  piece indices and the restoring trainer re-places them through its
  own partition machinery (elastic restore).

  **Two-phase commit**: every shard lands via tmp → fsync → rename;
  then (after a cross-process barrier when more than one process owns
  shards) process 0 publishes ``MANIFEST.json`` — shard list, sizes,
  whole-file CRC32s, epoch, fingerprint — itself via tmp → fsync →
  rename + a directory fsync.  A checkpoint without a committed
  manifest is INVISIBLE to the rotation's ``restore_latest``, so
  death at any byte offset of the save leaves either the previous
  complete checkpoint or the new complete one — never a torn read.
  Restore validates the manifest, every listed shard's existence +
  file CRC, every member CRC against the shard header, and full
  piece coverage of every array before anything touches the trainer.

v1/v2 single-file checkpoints still load, each with a loud
``resilience`` event (v1: no validation possible; v2: legacy format,
migrated to v3 on the next save).

Both trainers share this module; the async saver
(:mod:`roc_tpu.resilience.async_save`) snapshots on the step path via
:func:`snapshot_trainer` and runs :func:`write_snapshot` (CRC + write
+ commit) on its background thread.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.events import emit
from ..train.optimizer import AdamState

CHECKPOINT_VERSION = 3
_HEADER_KEY = "__header__"
MANIFEST_NAME = "MANIFEST.json"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity (CRC32/structure/coverage) or
    strict config-fingerprint validation.  Distinct from load errors
    of a missing file: the rotation layer catches this and falls back
    to the previous checkpoint."""


def _flatten(tree: Any, prefix: str) -> Dict[str, np.ndarray]:
    out = {}
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        key = prefix + jax.tree_util.keystr(path)
        out[key] = np.asarray(leaf)
    return out


def _unflatten(tree_template: Any, data, prefix: str, path: str) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(tree_template)
    paths = [p for p, _ in jax.tree_util.tree_leaves_with_path(
        tree_template)]
    new_leaves = []
    for kpath, tmpl in zip(paths, leaves):
        key = prefix + jax.tree_util.keystr(kpath)
        if key not in data:
            raise CheckpointCorrupt(
                f"{path}: missing array {key!r} (template/"
                f"checkpoint mismatch)")
        arr = data[key]
        if arr.shape != tuple(tmpl.shape):
            raise CheckpointCorrupt(
                f"{path}: shape mismatch at {key}: "
                f"{arr.shape} vs {tmpl.shape}")
        new_leaves.append(jnp.asarray(arr, dtype=tmpl.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _fsync_dir(d: str) -> None:
    """Make a completed rename durable: the rename itself is not on
    disk until the DIRECTORY entry is (process death alone never
    needed this; power loss did)."""
    dfd = os.open(d, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def params_signature(params: Any) -> str:
    """The param-tree identity hash (paths + shapes + dtypes) — the
    ``params_sig`` member of the strict fingerprint half.  ONE
    derivation shared by :func:`trainer_fingerprint` and the serve
    export (``roc_tpu/serve/export.py`` embeds it in the serving
    manifest), so a checkpoint and the artifact exported from it can
    never disagree about what the weights are."""
    import hashlib
    sigs = [f"{jax.tree_util.keystr(p)}:"
            f"{tuple(int(d) for d in leaf.shape)}:{leaf.dtype}"
            for p, leaf in
            jax.tree_util.tree_leaves_with_path(params)]
    return hashlib.sha1("|".join(sigs).encode()).hexdigest()[:16]


def trainer_fingerprint(trainer) -> Dict[str, Any]:
    """The saving/restoring trainer's identity, in two halves:

    - ``strict`` — what a checkpoint can never survive changing: the
      param-tree signature (paths + shapes + dtypes), the param/
      compute dtypes, and the dataset's V/E.  A mismatch is a
      :class:`CheckpointCorrupt` at restore.
    - ``elastic`` — what an elastic restart may legally change: the
      partition count and its quantized plan shapes
      (``quantize_plan_shapes`` output, carried on the
      PartitionedGraph) plus the resolved residency knobs.  A
      mismatch restores anyway (the v3 loader gathers the saved
      layout back to full host arrays, which are partition-
      independent) and leaves a dated resilience event.
    """
    strict: Dict[str, Any] = {
        "params_sig": params_signature(trainer.params)}
    cfg = getattr(trainer, "config", None)
    if cfg is not None:
        strict["dtype"] = str(jnp.dtype(cfg.dtype))
        strict["compute_dtype"] = (
            None if cfg.compute_dtype is None
            else str(jnp.dtype(cfg.compute_dtype)))
    ds = getattr(trainer, "_fp_dataset", None)
    if ds:
        strict["dataset"] = {k: int(v) for k, v in ds.items()}
    pg = getattr(trainer, "pg", None)
    elastic: Dict[str, Any] = {
        "num_parts": int(pg.num_parts) if pg is not None else 1,
        "part_nodes": int(pg.part_nodes) if pg is not None else None,
        "part_edges": int(pg.part_edges) if pg is not None else None}
    if cfg is not None:
        elastic.update(aggr_impl=cfg.aggr_impl, halo=cfg.halo,
                       features=cfg.features,
                       mesh=getattr(cfg, "mesh", "auto"))
    return {"strict": strict, "elastic": elastic}


# --------------------------------------------------- v3: host snapshot

def shard_file_name(proc: int) -> str:
    return f"shard_{int(proc):05d}.npz"


@dataclass
class _Piece:
    """One contiguous block of one array, owned by THIS process.
    ``index`` is the per-dim ``[lo, hi)`` range in the global array
    (None = the full array)."""
    member: str
    key: str
    index: Optional[List[List[int]]]
    data: np.ndarray


@dataclass
class Snapshot:
    """A host-side state snapshot, fully decoupled from the trainer
    and from jax: :func:`write_snapshot` (CRC + write + commit) can
    run it on the async saver thread while training dispatches the
    next epoch."""
    epoch: int
    proc: int
    writer_procs: List[int]
    pieces: List[_Piece]
    arrays: Dict[str, Dict[str, Any]]
    fingerprint: Dict[str, Any]
    block_ms: float = 0.0
    label: str = ""
    stats: Dict[str, Any] = field(default_factory=dict)


def _spec_of(leaf) -> List[Any]:
    """The per-dimension mesh-axis spec (the PR-14 sharding-spec
    vocabulary: axis names like ``parts``/``model``, None =
    replicated along that dim), recorded in every shard header."""
    ndim = int(getattr(leaf, "ndim", 0))
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    out: List[Any] = []
    for i in range(ndim):
        e = spec[i] if spec is not None and i < len(spec) else None
        out.append(list(e) if isinstance(e, tuple) else
                   (str(e) if e is not None else None))
    return out


def _owner_procs(leaf) -> List[int]:
    """Process indices owning at least one canonical
    (``replica_id == 0``) shard of ``leaf`` — identical on every
    process (derived from the GLOBAL sharding), which is what lets
    the commit protocol decide barrier-or-not without communicating.
    Host arrays / fully replicated arrays are owned by process 0."""
    if getattr(leaf, "is_fully_replicated", True):
        return [0]
    try:
        procs = sorted({s.device.process_index
                        for s in leaf.global_shards
                        if s.replica_id == 0})
        return procs or [0]
    except Exception:  # noqa: BLE001 - no global view: local owner
        return [int(jax.process_index())]


def _owns_pieces(leaf, proc: int) -> bool:
    """Whether THIS process owns any canonical piece of ``leaf`` —
    the gate in front of every device→host byte: a non-owner must
    never pay D2H traffic for arrays it will not write (the v2
    early-return contract, kept at per-leaf granularity)."""
    if getattr(leaf, "is_fully_replicated", True):
        return proc == 0
    return any(s.replica_id == 0 for s in leaf.addressable_shards)


def _leaf_pieces(key: str, leaf, proc: int) -> List[_Piece]:
    """THIS process's canonical pieces of ``leaf``."""
    if getattr(leaf, "is_fully_replicated", True):
        if proc != 0:
            return []
        return [_Piece(member=key, key=key, index=None,
                       data=np.asarray(leaf))]
    out: List[_Piece] = []
    shape = tuple(int(d) for d in leaf.shape)
    n = 0
    for s in leaf.addressable_shards:
        if s.replica_id != 0:
            continue
        index = [[int(sl.start or 0),
                  int(sl.stop) if sl.stop is not None else dim]
                 for sl, dim in zip(s.index, shape)]
        out.append(_Piece(member=f"{key}@{n}", key=key, index=index,
                          data=np.asarray(s.data)))
        n += 1
    return out


def snapshot_state(params: Any, opt_state: Any, epoch: int,
                   key: Optional[jax.Array] = None,
                   fingerprint: Optional[Dict[str, Any]] = None
                   ) -> Snapshot:
    """Host snapshot of the full training state: the ONLY part of a
    v3 save that must run on the step path (device → host reads; the
    arrays may be donated into the very next step).  D2H copies are
    issued asynchronously for every leaf first, then gathered — the
    per-leaf transfers overlap each other."""
    t0 = time.perf_counter()
    proc = int(jax.process_index())
    flat: List[Tuple[str, Any]] = []
    for prefix, tree in (("params", params), ("opt", opt_state)):
        for kpath, leaf in jax.tree_util.tree_leaves_with_path(tree):
            flat.append((prefix + jax.tree_util.keystr(kpath), leaf))
    for _, leaf in flat:
        if hasattr(leaf, "copy_to_host_async") and \
                _owns_pieces(leaf, proc):
            # best-effort overlap of the D2H issue across leaves —
            # OWNED leaves only (a non-owner process fetching bytes
            # it will never write would put full-tree D2H traffic on
            # every peer's step path); the np.asarray below is the
            # authoritative (blocking) fetch
            try:
                leaf.copy_to_host_async()
            except Exception:  # noqa: BLE001  # roc-lint: ok=swallowed-exception (an unsupported async copy just degrades to the sync fetch below)
                pass
    pieces: List[_Piece] = []
    arrays: Dict[str, Dict[str, Any]] = {}
    owners: set = set()
    for k, leaf in flat:
        arrays[k] = {"shape": [int(d) for d in leaf.shape],
                     "dtype": str(leaf.dtype),
                     "spec": _spec_of(leaf)}
        owners.update(_owner_procs(leaf))
        pieces.extend(_leaf_pieces(k, leaf, proc))
    # loop counters ride as ordinary process-0 members
    scalars: List[Tuple[str, np.ndarray]] = [
        ("__epoch__", np.asarray(epoch, dtype=np.int64))]
    if key is not None:
        scalars.append(("__key__", np.asarray(jax.device_get(key))))
    for k, arr in scalars:
        arrays[k] = {"shape": [int(d) for d in arr.shape],
                     "dtype": str(arr.dtype),
                     "spec": [None] * arr.ndim}
        if proc == 0:
            pieces.append(_Piece(member=k, key=k, index=None, data=arr))
    owners.add(0)
    return Snapshot(epoch=int(epoch), proc=proc,
                    writer_procs=sorted(owners), pieces=pieces,
                    arrays=arrays, fingerprint=fingerprint or {},
                    block_ms=(time.perf_counter() - t0) * 1e3)


def snapshot_trainer(trainer) -> Snapshot:
    """Trainer state → :class:`Snapshot` (the async saver's submit
    payload).  The finite guard is the CALLER's job (checkpoint_
    trainer / CheckpointRotation.save run it right before this)."""
    return snapshot_state(trainer.params, trainer.opt_state,
                          trainer.epoch, getattr(trainer, "key", None),
                          fingerprint=trainer_fingerprint(trainer))


# ------------------------------------------- v3: write + 2-phase commit

def _write_shard(d: str, snap: Snapshot) -> Tuple[str, bytes]:
    """Serialize THIS process's pieces and land them as
    ``shard_<proc>.npz`` via tmp → fsync → rename.  Returns the shard
    file name and its exact bytes (the manifest CRCs the same bytes —
    no re-read, no TOCTOU)."""
    from ..resilience import inject
    name = shard_file_name(snap.proc)
    data = {p.member: p.data for p in snap.pieces}
    header = {
        "version": CHECKPOINT_VERSION,
        "process": snap.proc,
        "epoch": snap.epoch,
        "crc32": {m: _crc(a) for m, a in data.items()},
        "arrays": snap.arrays,
        "pieces": {p.member: {"key": p.key, "index": p.index}
                   for p in snap.pieces},
    }
    data[_HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **data)
    raw = buf.getvalue()
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        # fault drill site: a SIGKILL here leaves only the .npz.tmp —
        # which restore structurally never picks up (atomicity drill)
        inject.maybe_kill_in_save(snap.epoch)
        os.replace(tmp, os.path.join(d, name))
        _fsync_dir(d)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return name, raw


def commit_manifest(d: str, snap: Snapshot,
                    shards: List[Dict[str, Any]]) -> None:
    """Phase two: publish ``MANIFEST.json`` atomically (tmp → fsync →
    rename → directory fsync).  The manifest IS the commit record —
    until it lands, the checkpoint does not exist to any reader."""
    doc = {"version": CHECKPOINT_VERSION,
           "epoch": snap.epoch,
           "fingerprint": snap.fingerprint,
           "shards": shards}
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(d, MANIFEST_NAME))
        _fsync_dir(d)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def write_snapshot(path: str, snap: Snapshot) -> Dict[str, Any]:
    """The full v3 save (CRC + shard write + commit) for an already-
    taken snapshot — jax-free unless more than one process owns
    shards (then the commit barrier), so the async saver can run it
    on its background thread.  Crash-consistent at every byte: shards
    land via atomic rename, the manifest publishes last, and an
    uncommitted (or half-rewritten) directory is invisible to
    ``restore_latest``."""
    from ..resilience import inject
    t0 = time.perf_counter()
    d = os.path.abspath(path)
    os.makedirs(d, exist_ok=True)
    man = os.path.join(d, MANIFEST_NAME)
    if snap.proc == 0 and os.path.exists(man):
        # re-saving a replayed epoch: UN-commit first so a crash mid-
        # rewrite leaves an invisible directory, never a manifest
        # pointing at half-replaced shards
        os.remove(man)
        _fsync_dir(d)
    if len(snap.writer_procs) > 1:
        # un-commit barrier: no writer may rename its shard into
        # place while a previous manifest could still reference the
        # old bytes — without this, a peer's early os.replace races
        # proc 0's un-commit and a crash in that window leaves a live
        # manifest over a half-replaced shard set (found by the
        # level-eight model checker's ckpt-commit model; CRC
        # validation at restore would detect it, but the ordering
        # guarantee is what makes a present manifest ALWAYS valid)
        from ..parallel.multihost import checkpoint_commit_barrier
        checkpoint_commit_barrier(
            f"{os.path.basename(d)}:{snap.epoch}:uncommit")
    my_name = my_raw = None
    if snap.pieces:
        my_name, my_raw = _write_shard(d, snap)
    t_write = time.perf_counter()
    # fault drill site: the exact two-phase-commit window — shards
    # renamed into place, manifest not yet published
    inject.maybe_kill_in_commit(snap.epoch)
    if len(snap.writer_procs) > 1:
        from ..parallel.multihost import checkpoint_commit_barrier
        checkpoint_commit_barrier(f"{os.path.basename(d)}:{snap.epoch}")
    if snap.proc == 0:
        shards = []
        for p in snap.writer_procs:
            name = shard_file_name(p)
            if name == my_name:
                raw = my_raw
            else:
                # a peer's shard, already landed (barrier above) on
                # the shared checkpoint storage
                with open(os.path.join(d, name), "rb") as f:
                    raw = f.read()
            shards.append({"file": name, "process": int(p),
                           "bytes": len(raw),
                           "crc32": zlib.crc32(raw) & 0xFFFFFFFF})
        commit_manifest(d, snap, shards)
    t_commit = time.perf_counter()
    stats = {"epoch": snap.epoch, "path": d,
             "block_ms": round(snap.block_ms, 3),
             "write_ms": round((t_write - t0) * 1e3, 3),
             "commit_ms": round((t_commit - t_write) * 1e3, 3),
             "save_ms": round((t_commit - t0) * 1e3 + snap.block_ms, 3),
             "bytes": len(my_raw) if my_raw is not None else 0,
             "shards": len(snap.writer_procs)}
    snap.stats = stats
    return stats


def save_checkpoint(path: str, params: Any, opt_state: AdamState,
                    epoch: int, key: Optional[jax.Array] = None,
                    fingerprint: Optional[Dict[str, Any]] = None
                    ) -> None:
    """Synchronous v3 save: snapshot + CRC + shard write + manifest
    commit, all on the calling thread.  Every process calls this
    under multi-process SPMD; each writes only the shards it owns and
    process 0 publishes the commit record."""
    snap = snapshot_state(params, opt_state, epoch, key=key,
                          fingerprint=fingerprint)
    write_snapshot(path, snap)


# ------------------------------------------------------------ loaders

def _read_checkpoint(path: str) -> Dict[str, np.ndarray]:
    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except FileNotFoundError:
        raise
    except Exception as e:
        # torn write, zip-CRC failure, truncation: all one corruption
        # class for the rotation's fallback
        raise CheckpointCorrupt(
            f"{path}: unreadable ({type(e).__name__}: {e})") from e


def _parse_header(data: Dict[str, np.ndarray],
                  path: str) -> Optional[Dict[str, Any]]:
    raw = data.pop(_HEADER_KEY, None)
    if raw is None:
        return None
    try:
        return json.loads(bytes(
            np.asarray(raw, dtype=np.uint8)).decode("utf-8"))
    except Exception as e:
        raise CheckpointCorrupt(
            f"{path}: integrity header unparseable "
            f"({type(e).__name__}: {e})") from e


def _validate_integrity(data: Dict[str, np.ndarray],
                        header: Dict[str, Any], path: str) -> None:
    crcs = header.get("crc32") or {}
    missing = sorted(set(crcs) - set(data))
    extra = sorted(set(data) - set(crcs))
    if missing or extra:
        raise CheckpointCorrupt(
            f"{path}: array set mismatch (missing={missing}, "
            f"unexpected={extra})")
    for name, want in crcs.items():
        got = _crc(data[name])
        if got != int(want):
            raise CheckpointCorrupt(
                f"{path}: CRC32 mismatch at {name!r} "
                f"({got:#010x} != {int(want):#010x})")


def _validate_fingerprint(header: Dict[str, Any],
                          expect: Optional[Dict[str, Any]],
                          path: str) -> None:
    saved = header.get("fingerprint") or {}
    if not expect or not saved:
        return
    ss, es = saved.get("strict") or {}, expect.get("strict") or {}
    bad = sorted(k for k in set(ss) & set(es) if ss[k] != es[k])
    if bad:
        raise CheckpointCorrupt(
            f"{path}: config fingerprint mismatch at {bad} — this "
            f"checkpoint belongs to a different model/dataset/dtype "
            f"(saved {({k: ss[k] for k in bad})}, "
            f"restoring {({k: es[k] for k in bad})})")
    sv, ev = saved.get("elastic") or {}, expect.get("elastic") or {}
    if sv and ev and sv != ev:
        emit("resilience",
             f"elastic restore: checkpoint partition "
             f"P={sv.get('num_parts')} "
             f"({sv.get('part_nodes')}x{sv.get('part_edges')}) -> "
             f"current P={ev.get('num_parts')} "
             f"({ev.get('part_nodes')}x{ev.get('part_edges')}); "
             f"restored arrays are gathered to full host layout, the "
             f"partition is rebuilt from the current plan",
             kind="elastic_restore", saved=sv, current=ev)


def read_manifest(path: str) -> Dict[str, Any]:
    """The committed manifest of a v3 checkpoint directory, or
    :class:`CheckpointCorrupt` — an uncommitted directory IS the
    corruption class (it must be invisible to the fallback scan)."""
    man = os.path.join(path, MANIFEST_NAME)
    try:
        with open(man) as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise CheckpointCorrupt(
            f"{path}: no committed manifest (save died before the "
            f"commit, or not a checkpoint directory)") from None
    except Exception as e:
        raise CheckpointCorrupt(
            f"{man}: manifest unreadable "
            f"({type(e).__name__}: {e})") from e
    if not isinstance(doc, dict) or \
            doc.get("version") != CHECKPOINT_VERSION or \
            not isinstance(doc.get("shards"), list) or not doc["shards"]:
        raise CheckpointCorrupt(f"{man}: malformed manifest")
    return doc


def is_committed(path: str) -> bool:
    """Cheap commit test for rotation scans (existence only; full
    validation happens on the restore attempt, which never touches
    the trainer before it passes)."""
    return os.path.isdir(path) and \
        os.path.exists(os.path.join(path, MANIFEST_NAME))


def _load_v3(path: str) -> Tuple[Dict[str, np.ndarray],
                                 Dict[str, Any]]:
    """Validate + gather a v3 checkpoint directory back to full host
    arrays.  EVERY manifest-listed shard is checked — existence, byte
    count, whole-file CRC32, per-member CRC32 against the shard
    header, and full piece coverage of every array — BEFORE any data
    is returned, so a manifest whose shard went missing can never be
    selected by the fallback scan."""
    doc = read_manifest(path)
    pieces: Dict[str, List[Tuple[Optional[List[List[int]]],
                                 np.ndarray]]] = {}
    metas: Dict[str, Dict[str, Any]] = {}
    for sh in doc["shards"]:
        fp = os.path.join(path, str(sh.get("file")))
        try:
            with open(fp, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorrupt(
                f"{path}: manifest lists {sh.get('file')} but the "
                f"shard is missing/unreadable ({e})") from e
        if len(raw) != int(sh.get("bytes", -1)) or \
                (zlib.crc32(raw) & 0xFFFFFFFF) != int(sh.get("crc32",
                                                             -1)):
            raise CheckpointCorrupt(
                f"{fp}: shard bytes/CRC32 do not match the committed "
                f"manifest")
        try:
            with np.load(io.BytesIO(raw)) as z:
                data = {k: z[k] for k in z.files}
        except Exception as e:
            raise CheckpointCorrupt(
                f"{fp}: unreadable ({type(e).__name__}: {e})") from e
        header = _parse_header(data, fp)
        if header is None:
            raise CheckpointCorrupt(f"{fp}: shard has no header")
        _validate_integrity(data, header, fp)
        metas.update(header.get("arrays") or {})
        for member, pm in (header.get("pieces") or {}).items():
            pieces.setdefault(pm["key"], []).append(
                (pm.get("index"), data[member]))
    out: Dict[str, np.ndarray] = {}
    for key, meta in metas.items():
        ps = pieces.get(key, [])
        shape = tuple(int(d) for d in meta["shape"])
        total = int(np.prod(shape)) if shape else 1
        if len(ps) == 1 and ps[0][0] is None:
            out[key] = ps[0][1]
            continue
        full = np.zeros(shape, dtype=np.dtype(meta["dtype"]))
        covered = 0
        for index, arr in ps:
            if index is None:
                full[...] = arr
                covered += int(arr.size)
                continue
            full[tuple(slice(lo, hi) for lo, hi in index)] = arr
            covered += int(arr.size)
        if covered != total:
            # gather-on-restore coverage proof: pieces are disjoint
            # by construction (replica_id-0 dedup), so count equality
            # == every element restored exactly once
            raise CheckpointCorrupt(
                f"{path}: array {key!r} gathered {covered}/{total} "
                f"elements from the saved shards (incomplete "
                f"sharded save)")
        out[key] = full
    return out, doc


def _load_legacy_file(path: str) -> Tuple[Dict[str, np.ndarray],
                                          Dict[str, Any]]:
    """v1/v2 single-file loader, each with its loud migration
    warning."""
    data = _read_checkpoint(path)
    header = _parse_header(data, path)
    if header is None:
        emit("resilience",
             f"{os.path.basename(path)}: v1 checkpoint (no integrity "
             f"header) — loading WITHOUT CRC/fingerprint validation",
             kind="v1_checkpoint", path=path)
        return data, {}
    emit("resilience",
         f"{os.path.basename(path)}: legacy v2 single-file "
         f"checkpoint — loading (validated); the next save writes "
         f"the sharded v3 directory format",
         kind="legacy_checkpoint", path=path, version=2)
    _validate_integrity(data, header, path)
    return data, header


def load_checkpoint(path: str, params_template: Any,
                    opt_template: AdamState,
                    expect_fingerprint: Optional[Dict[str, Any]] = None
                    ) -> Tuple[Any, AdamState, int, Optional[jax.Array]]:
    """Restore against templates (e.g. a fresh ``model.init_params`` +
    ``adam_init``); shapes are validated leaf by leaf, every byte
    against the stored CRC32 tables (v3: manifest file CRCs + shard
    member CRCs + coverage; v2: the header table), and the strict
    fingerprint half against ``expect_fingerprint`` — all failures
    raise :class:`CheckpointCorrupt` before anything is returned.
    v1/v2 single-file checkpoints load with a loud warning."""
    if os.path.isdir(path):
        data, doc = _load_v3(path)
        header: Dict[str, Any] = doc
    else:
        data, header = _load_legacy_file(path)
    _validate_fingerprint(header, expect_fingerprint, path)
    params = _unflatten(params_template, data, "params", path)
    opt_state = _unflatten(opt_template, data, "opt", path)
    epoch = int(data["__epoch__"])
    key = jnp.asarray(data["__key__"]) if "__key__" in data else None
    return params, opt_state, epoch, key


def restore_params_only(path: str
                        ) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """``(params, fingerprint, epoch)`` from a checkpoint WITHOUT
    constructing a trainer: params come back as the flat name → array
    dict every model's ``init_params`` produces, integrity-validated
    (v3: full manifest + shard validation; v2: the CRC table;
    optimizer state is read past, never materialized on device).  The
    serve export CLI and a cold server process read weights through
    this — paying trainer/dataset setup just to load a checkpoint
    would put minutes of graph-table builds on a path that needs none
    of them.  ``fingerprint`` is the saved fingerprint dict (empty
    for v1 checkpoints) — callers hold its strict half against the
    model they are about to serve."""
    import re
    if os.path.isdir(path):
        data, header = _load_v3(path)
    else:
        data, header = _load_legacy_file(path)
    params: Dict[str, Any] = {}
    # one single-quoted bracket segment ONLY: a nested tree flattens
    # to params['a']['b'], which a greedy (.+) would silently mangle
    # into one corrupt name — such keys must hit the loud error below
    key_re = re.compile(r"^params\['([^']+)'\]$")
    bad = []
    for k, v in data.items():
        if not k.startswith("params"):
            continue
        m = key_re.match(k)
        if m:
            params[m.group(1)] = jnp.asarray(v)
        else:
            bad.append(k)
    if bad or not params:
        raise CheckpointCorrupt(
            f"{path}: expected flat params['<name>'] arrays — not a "
            f"trainer checkpoint, or a non-flat param tree this "
            f"loader does not speak"
            + (f" (unparsed keys: {bad[:3]})" if bad else ""))
    epoch = int(data["__epoch__"]) if "__epoch__" in data else 0
    fingerprint = (header or {}).get("fingerprint") or {}
    return params, fingerprint, epoch


def restore_trainer(trainer, path: str) -> None:
    """Resume a Trainer/DistributedTrainer in place.  The v3 loader
    gathers whatever (P, mesh) layout was saved back to full host
    arrays; distributed trainers then re-replicate across their mesh
    (multihost-safe: ``put_replicated`` assembles from addressable
    shards) — the partition itself was already rebuilt by the
    trainer's own constructor, so a checkpoint from a different P
    restores cleanly (elastic restart)."""
    params, opt_state, epoch, key = load_checkpoint(
        path, trainer.params, trainer.opt_state,
        expect_fingerprint=trainer_fingerprint(trainer))
    mesh = getattr(trainer, "mesh", None)
    if mesh is not None:
        from ..parallel.distributed import put_replicated
        params, opt_state = put_replicated((params, opt_state), mesh)
    trainer.params = params
    trainer.opt_state = opt_state
    trainer.epoch = epoch
    if key is not None:
        trainer.key = key


def checkpoint_trainer(trainer, path: str) -> None:
    """Save a trainer's state synchronously (format v3).  EVERY
    trainer save passes the finite guard first (params + opt state in
    one jitted reduction, one device sync — resilience/recovery.
    check_params_finite): a poisoned state must never persist,
    whether the save came from the recovery rotation, the CLI's
    --checkpoint paths, or an emergency preemption save.  Under
    multi-process SPMD every process participates — each writes only
    the shard file it owns (``shard_<proc>.npz``, the per-process
    filename the artifact-lock lint demands) and process 0
    (``jax.process_index() == 0``) publishes the commit manifest;
    with today's fully replicated state that degenerates to process 0
    writing everything, the v2 single-writer handshake."""
    from ..resilience.recovery import check_params_finite
    check_params_finite(trainer.params, trainer.opt_state)
    snap = snapshot_trainer(trainer)
    if jax.process_count() > 1 and jax.process_index() != 0 and \
            not snap.pieces:
        # nothing owned here and no barrier expected: the replicated
        # degenerate case keeps the v2 early return
        if len(snap.writer_procs) <= 1:
            return
    write_snapshot(path, snap)
