"""Checkpoint / resume.

The reference has **no** persistence at all (SURVEY §5: weights are never
saved; the only cache is the feature-CSV binary).  This fills that gap
with a minimal, dependency-light checkpointer: the params pytree, Adam
state, epoch counter and PRNG key are flattened to a single ``.npz``
(atomic rename on save), restored against a template built from the
model — robust across JAX versions and trivially inspectable.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..train.optimizer import AdamState


def _flatten(tree: Any, prefix: str) -> Dict[str, np.ndarray]:
    out = {}
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        key = prefix + jax.tree_util.keystr(path)
        out[key] = np.asarray(leaf)
    return out


def _unflatten(tree_template: Any, data, prefix: str) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(tree_template)
    paths = [p for p, _ in jax.tree_util.tree_leaves_with_path(
        tree_template)]
    new_leaves = []
    for path, tmpl in zip(paths, leaves):
        key = prefix + jax.tree_util.keystr(path)
        arr = data[key]
        assert arr.shape == tuple(tmpl.shape), (
            f"checkpoint/model mismatch at {key}: "
            f"{arr.shape} vs {tmpl.shape}")
        new_leaves.append(jnp.asarray(arr, dtype=tmpl.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_checkpoint(path: str, params: Any, opt_state: AdamState,
                    epoch: int, key: Optional[jax.Array] = None) -> None:
    """Atomically write params + optimizer state + loop counters."""
    data = _flatten(jax.device_get(params), "params")
    data.update(_flatten(jax.device_get(opt_state), "opt"))
    data["__epoch__"] = np.asarray(epoch, dtype=np.int64)
    if key is not None:
        data["__key__"] = np.asarray(jax.device_get(key))
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **data)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_checkpoint(path: str, params_template: Any,
                    opt_template: AdamState
                    ) -> Tuple[Any, AdamState, int, Optional[jax.Array]]:
    """Restore against templates (e.g. a fresh ``model.init_params`` +
    ``adam_init``); shapes are validated leaf by leaf."""
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    params = _unflatten(params_template, data, "params")
    opt_state = _unflatten(opt_template, data, "opt")
    epoch = int(data["__epoch__"])
    key = jnp.asarray(data["__key__"]) if "__key__" in data else None
    return params, opt_state, epoch, key


def restore_trainer(trainer, path: str) -> None:
    """Resume a Trainer/DistributedTrainer in place."""
    params, opt_state, epoch, key = load_checkpoint(
        path, trainer.params, trainer.opt_state)
    trainer.params = params
    trainer.opt_state = opt_state
    trainer.epoch = epoch
    if key is not None:
        trainer.key = key


def checkpoint_trainer(trainer, path: str) -> None:
    save_checkpoint(path, trainer.params, trainer.opt_state,
                    trainer.epoch, trainer.key)
