"""Checkpoint / resume with integrity + identity validation.

The reference has **no** persistence at all (SURVEY §5: weights are
never saved; the only cache is the feature-CSV binary).  This fills
that gap with a minimal, dependency-light checkpointer: the params
pytree, Adam state, epoch counter and PRNG key are flattened to a
single ``.npz`` (atomic rename on save), restored against a template
built from the model — robust across JAX versions and trivially
inspectable.

Format v2 (resilience PR) hardens the file itself:

- a JSON ``__header__`` member carries the format version, a
  **per-array CRC32** table, and the saving trainer's **config
  fingerprint** — the resolve signature (dtype, impl/halo/features)
  plus the quantized partition-plan shapes
  (``core/partition.quantize_plan_shapes`` via ``pg.part_nodes/
  part_edges``);
- restore validates every CRC and the *strict* fingerprint half
  (model/dataset/dtype identity) and raises a distinct
  :class:`CheckpointCorrupt` on any mismatch — the guard for the
  observed bit-rot/denormal-garbage corruption class (CHANGES.md
  PR 7);
- the *elastic* fingerprint half (partition count + quantized plan
  shapes) may differ: replicated params ride through untouched while
  the restoring trainer rebuilds its partition — that IS the elastic
  restart onto a different P, announced with a dated ``resilience``
  event;
- v1 checkpoints (no header) still load, with a loud warning.

Both trainers share this module: the distributed/multihost path
writes the replicated state ONCE (process 0) and every process
restores through ``put_replicated``.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.events import emit
from ..train.optimizer import AdamState

CHECKPOINT_VERSION = 2
_HEADER_KEY = "__header__"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity (CRC32/structure) or strict
    config-fingerprint validation.  Distinct from load errors of a
    missing file: the rotation layer catches this and falls back to
    the previous checkpoint."""


def _flatten(tree: Any, prefix: str) -> Dict[str, np.ndarray]:
    out = {}
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        key = prefix + jax.tree_util.keystr(path)
        out[key] = np.asarray(leaf)
    return out


def _unflatten(tree_template: Any, data, prefix: str, path: str) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(tree_template)
    paths = [p for p, _ in jax.tree_util.tree_leaves_with_path(
        tree_template)]
    new_leaves = []
    for kpath, tmpl in zip(paths, leaves):
        key = prefix + jax.tree_util.keystr(kpath)
        if key not in data:
            raise CheckpointCorrupt(
                f"{path}: missing array {key!r} (template/"
                f"checkpoint mismatch)")
        arr = data[key]
        if arr.shape != tuple(tmpl.shape):
            raise CheckpointCorrupt(
                f"{path}: shape mismatch at {key}: "
                f"{arr.shape} vs {tmpl.shape}")
        new_leaves.append(jnp.asarray(arr, dtype=tmpl.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def params_signature(params: Any) -> str:
    """The param-tree identity hash (paths + shapes + dtypes) — the
    ``params_sig`` member of the strict fingerprint half.  ONE
    derivation shared by :func:`trainer_fingerprint` and the serve
    export (``roc_tpu/serve/export.py`` embeds it in the serving
    manifest), so a checkpoint and the artifact exported from it can
    never disagree about what the weights are."""
    import hashlib
    sigs = [f"{jax.tree_util.keystr(p)}:"
            f"{tuple(int(d) for d in leaf.shape)}:{leaf.dtype}"
            for p, leaf in
            jax.tree_util.tree_leaves_with_path(params)]
    return hashlib.sha1("|".join(sigs).encode()).hexdigest()[:16]


def trainer_fingerprint(trainer) -> Dict[str, Any]:
    """The saving/restoring trainer's identity, in two halves:

    - ``strict`` — what a checkpoint can never survive changing: the
      param-tree signature (paths + shapes + dtypes), the param/
      compute dtypes, and the dataset's V/E.  A mismatch is a
      :class:`CheckpointCorrupt` at restore.
    - ``elastic`` — what an elastic restart may legally change: the
      partition count and its quantized plan shapes
      (``quantize_plan_shapes`` output, carried on the
      PartitionedGraph) plus the resolved residency knobs.  A
      mismatch restores anyway (replicated params are partition-
      independent) and leaves a dated resilience event.
    """
    strict: Dict[str, Any] = {
        "params_sig": params_signature(trainer.params)}
    cfg = getattr(trainer, "config", None)
    if cfg is not None:
        strict["dtype"] = str(jnp.dtype(cfg.dtype))
        strict["compute_dtype"] = (
            None if cfg.compute_dtype is None
            else str(jnp.dtype(cfg.compute_dtype)))
    ds = getattr(trainer, "_fp_dataset", None)
    if ds:
        strict["dataset"] = {k: int(v) for k, v in ds.items()}
    pg = getattr(trainer, "pg", None)
    elastic: Dict[str, Any] = {
        "num_parts": int(pg.num_parts) if pg is not None else 1,
        "part_nodes": int(pg.part_nodes) if pg is not None else None,
        "part_edges": int(pg.part_edges) if pg is not None else None}
    if cfg is not None:
        elastic.update(aggr_impl=cfg.aggr_impl, halo=cfg.halo,
                       features=cfg.features)
    return {"strict": strict, "elastic": elastic}


def save_checkpoint(path: str, params: Any, opt_state: AdamState,
                    epoch: int, key: Optional[jax.Array] = None,
                    fingerprint: Optional[Dict[str, Any]] = None
                    ) -> None:
    """Atomically write params + optimizer state + loop counters, with
    a v2 integrity header (per-array CRC32 + config fingerprint)."""
    data = _flatten(jax.device_get(params), "params")
    data.update(_flatten(jax.device_get(opt_state), "opt"))
    data["__epoch__"] = np.asarray(epoch, dtype=np.int64)
    if key is not None:
        data["__key__"] = np.asarray(jax.device_get(key))
    header = {"version": CHECKPOINT_VERSION,
              "crc32": {k: _crc(v) for k, v in data.items()},
              "fingerprint": fingerprint or {}}
    data[_HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **data)
            f.flush()
            os.fsync(f.fileno())
        # fault drill site: a SIGKILL here leaves only the .npz.tmp —
        # which restore structurally never picks up (atomicity test)
        from ..resilience import inject
        inject.maybe_kill_in_save(epoch)
        os.replace(tmp, path)
        # the rename itself is not durable until the DIRECTORY entry
        # is on disk — without this a host crash after "checkpoint
        # saved" can still lose the file (process death alone cannot:
        # the kernel keeps completed renames)
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _read_checkpoint(path: str) -> Dict[str, np.ndarray]:
    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except FileNotFoundError:
        raise
    except Exception as e:
        # torn write, zip-CRC failure, truncation: all one corruption
        # class for the rotation's fallback
        raise CheckpointCorrupt(
            f"{path}: unreadable ({type(e).__name__}: {e})") from e


def _parse_header(data: Dict[str, np.ndarray],
                  path: str) -> Optional[Dict[str, Any]]:
    raw = data.pop(_HEADER_KEY, None)
    if raw is None:
        return None
    try:
        return json.loads(bytes(
            np.asarray(raw, dtype=np.uint8)).decode("utf-8"))
    except Exception as e:
        raise CheckpointCorrupt(
            f"{path}: integrity header unparseable "
            f"({type(e).__name__}: {e})") from e


def _validate_integrity(data: Dict[str, np.ndarray],
                        header: Dict[str, Any], path: str) -> None:
    crcs = header.get("crc32") or {}
    missing = sorted(set(crcs) - set(data))
    extra = sorted(set(data) - set(crcs))
    if missing or extra:
        raise CheckpointCorrupt(
            f"{path}: array set mismatch (missing={missing}, "
            f"unexpected={extra})")
    for name, want in crcs.items():
        got = _crc(data[name])
        if got != int(want):
            raise CheckpointCorrupt(
                f"{path}: CRC32 mismatch at {name!r} "
                f"({got:#010x} != {int(want):#010x})")


def _validate_fingerprint(header: Dict[str, Any],
                          expect: Optional[Dict[str, Any]],
                          path: str) -> None:
    saved = header.get("fingerprint") or {}
    if not expect or not saved:
        return
    ss, es = saved.get("strict") or {}, expect.get("strict") or {}
    bad = sorted(k for k in set(ss) & set(es) if ss[k] != es[k])
    if bad:
        raise CheckpointCorrupt(
            f"{path}: config fingerprint mismatch at {bad} — this "
            f"checkpoint belongs to a different model/dataset/dtype "
            f"(saved {({k: ss[k] for k in bad})}, "
            f"restoring {({k: es[k] for k in bad})})")
    sv, ev = saved.get("elastic") or {}, expect.get("elastic") or {}
    if sv and ev and sv != ev:
        emit("resilience",
             f"elastic restore: checkpoint partition "
             f"P={sv.get('num_parts')} "
             f"({sv.get('part_nodes')}x{sv.get('part_edges')}) -> "
             f"current P={ev.get('num_parts')} "
             f"({ev.get('part_nodes')}x{ev.get('part_edges')}); "
             f"replicated params ride through, the partition is "
             f"rebuilt from the current plan", kind="elastic_restore",
             saved=sv, current=ev)


def load_checkpoint(path: str, params_template: Any,
                    opt_template: AdamState,
                    expect_fingerprint: Optional[Dict[str, Any]] = None
                    ) -> Tuple[Any, AdamState, int, Optional[jax.Array]]:
    """Restore against templates (e.g. a fresh ``model.init_params`` +
    ``adam_init``); shapes are validated leaf by leaf, array bytes
    against the stored CRC32 table, and the strict fingerprint half
    against ``expect_fingerprint`` — all failures raise
    :class:`CheckpointCorrupt`.  v1 checkpoints (no header) load with
    a loud warning instead of validation."""
    data = _read_checkpoint(path)
    header = _parse_header(data, path)
    if header is None:
        emit("resilience",
             f"{os.path.basename(path)}: v1 checkpoint (no integrity "
             f"header) — loading WITHOUT CRC/fingerprint validation",
             kind="v1_checkpoint", path=path)
    else:
        _validate_integrity(data, header, path)
        _validate_fingerprint(header, expect_fingerprint, path)
    params = _unflatten(params_template, data, "params", path)
    opt_state = _unflatten(opt_template, data, "opt", path)
    epoch = int(data["__epoch__"])
    key = jnp.asarray(data["__key__"]) if "__key__" in data else None
    return params, opt_state, epoch, key


def restore_params_only(path: str
                        ) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """``(params, fingerprint, epoch)`` from a checkpoint WITHOUT
    constructing a trainer: params come back as the flat name → array
    dict every model's ``init_params`` produces, integrity-validated
    against the v2 CRC table (optimizer state is read past, never
    materialized on device).  The serve export CLI and a cold server
    process read weights through this — paying trainer/dataset setup
    just to load an .npz would put minutes of graph-table builds on a
    path that needs none of them.  ``fingerprint`` is the saved v2
    fingerprint dict (empty for v1 checkpoints) — callers hold its
    strict half against the model they are about to serve."""
    import re
    data = _read_checkpoint(path)
    header = _parse_header(data, path)
    if header is None:
        emit("resilience",
             f"{os.path.basename(path)}: v1 checkpoint (no integrity "
             f"header) — loading WITHOUT CRC/fingerprint validation",
             kind="v1_checkpoint", path=path)
    else:
        _validate_integrity(data, header, path)
    params: Dict[str, Any] = {}
    # one single-quoted bracket segment ONLY: a nested tree flattens
    # to params['a']['b'], which a greedy (.+) would silently mangle
    # into one corrupt name — such keys must hit the loud error below
    key_re = re.compile(r"^params\['([^']+)'\]$")
    bad = []
    for k, v in data.items():
        if not k.startswith("params"):
            continue
        m = key_re.match(k)
        if m:
            params[m.group(1)] = jnp.asarray(v)
        else:
            bad.append(k)
    if bad or not params:
        raise CheckpointCorrupt(
            f"{path}: expected flat params['<name>'] arrays — not a "
            f"trainer checkpoint, or a non-flat param tree this "
            f"loader does not speak"
            + (f" (unparsed keys: {bad[:3]})" if bad else ""))
    epoch = int(data["__epoch__"]) if "__epoch__" in data else 0
    fingerprint = (header or {}).get("fingerprint") or {}
    return params, fingerprint, epoch


def restore_trainer(trainer, path: str) -> None:
    """Resume a Trainer/DistributedTrainer in place.  Distributed
    trainers re-replicate the restored host state across their mesh
    (multihost-safe: ``put_replicated`` assembles from addressable
    shards) — the partition itself was already rebuilt by the
    trainer's own constructor, so a checkpoint from a different P
    restores cleanly (elastic restart)."""
    params, opt_state, epoch, key = load_checkpoint(
        path, trainer.params, trainer.opt_state,
        expect_fingerprint=trainer_fingerprint(trainer))
    mesh = getattr(trainer, "mesh", None)
    if mesh is not None:
        from ..parallel.distributed import put_replicated
        params, opt_state = put_replicated((params, opt_state), mesh)
    trainer.params = params
    trainer.opt_state = opt_state
    trainer.epoch = epoch
    if key is not None:
        trainer.key = key


def checkpoint_trainer(trainer, path: str) -> None:
    """Save a trainer's state.  EVERY trainer save passes the finite
    guard first (params + opt state in one jitted reduction, one
    device sync — resilience/recovery.check_params_finite): a
    poisoned state must never persist, whether the save came from the
    recovery rotation, the CLI's --checkpoint paths, or an emergency
    preemption save.  Replicated distributed state is written ONCE
    per job: under multi-process SPMD only process 0 touches the
    filesystem (every process holds the same replicated values)."""
    from ..resilience.recovery import check_params_finite
    check_params_finite(trainer.params, trainer.opt_state)
    if jax.process_count() > 1 and jax.process_index() != 0:
        return
    save_checkpoint(path, trainer.params, trainer.opt_state,
                    trainer.epoch, getattr(trainer, "key", None),
                    fingerprint=trainer_fingerprint(trainer))
