"""Tracing / profiling / observability subsystem.

The reference has no profiling subsystem of its own — only Legion log
categories and commented-out ``Realm::Clock`` micro-timers
(``activation_kernel.cu:40,62-63``, ``gnn.cc:796-805``; SURVEY.md §5
calls this a gap to fill, not copy).  The TPU-native equivalents:

- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard/Perfetto trace directory (the analog of Legion's
  ``-lg:prof`` logs).
- :class:`annotate` — ``jax.profiler.TraceAnnotation`` wrapper so epoch
  phases (forward/backward/update/eval) show up as named spans.
- :class:`EpochTimer` — honest wall-clock epoch timing, plus named
  per-phase spans (train burst / eval / streamed-head sub-phases)
  recorded with the same fetch barrier.  Under the axon-tunneled TPU,
  ``block_until_ready`` does NOT synchronize, so ``sync`` fetches a
  scalar reduction of a device array — the only reliable barrier (see
  benchmarks/micro_agg.py).
- :class:`MetricsLog` — structured training-metrics history with JSONL
  export; the rebuild of the reference's stdout-only ``PerfMetrics``
  prints (``softmax_kernel.cu:141-152``) as a queryable artifact.

The structured event bus lives in ``roc_tpu/obs`` — this module stays
the low-level timing layer it feeds.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Profile the enclosed block into ``log_dir`` (TensorBoard trace
    format).  No-op when ``log_dir`` is falsy, so call sites can thread
    a config value through unconditionally."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span in profiler traces (forward/backward/update/eval)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def sync(x: Any) -> None:
    """Reliable device barrier: fetch a scalar derived from ``x``.
    ``jax.block_until_ready`` is not sufficient under the axon relay."""
    import jax
    import jax.numpy as jnp
    leaves = jax.tree_util.tree_leaves(x)
    if leaves:
        float(jnp.sum(leaves[0]))


@dataclass
class EpochTimer:
    """Wall-clock per-epoch timer with warmup separation and named
    per-phase spans.

    The first ``warmup`` laps (compile + cache effects) are recorded but
    excluded from the summary statistics.  ``span(name)`` records a
    phase (train burst, eval, halo exchange, streamed head
    forward/wgrad, optimizer update) into its own series — the host-
    visible analog of :func:`annotate`'s device-trace spans, summarized
    by :meth:`span_summary` as p50/p90 per phase.
    """

    warmup: int = 1
    laps_ms: List[float] = field(default_factory=list)
    spans_ms: Dict[str, List[float]] = field(default_factory=dict)
    # span-lap records for the cross-process timeline merger
    # (obs/timeline.py): ``(name, mono_start_s, dur_ms)`` per lap,
    # drained by :meth:`take_timeline` into periodic ``timeline``
    # events (train/trainer.py run_epoch_loop)
    timeline: List[tuple] = field(default_factory=list)
    # route spans through jax.profiler.TraceAnnotation too, so device
    # traces (--profile-dir) carry the same named phases as the host
    # timeline lanes; off by default (annotate imports jax)
    annotate: bool = False
    _t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, sync_on: Any = None) -> float:
        assert self._t0 is not None, "start() not called"
        if sync_on is not None:
            sync(sync_on)
        ms = (time.perf_counter() - self._t0) * 1e3
        self.laps_ms.append(ms)
        self._t0 = None
        return ms

    @contextlib.contextmanager
    def lap(self, sync_on: Any = None) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.stop(sync_on=sync_on)

    @contextlib.contextmanager
    def span(self, name: str, sync_on: Any = None) -> Iterator[None]:
        """Record one lap of the named phase.  To barrier on work
        dispatched INSIDE the span, pass ``sync_on`` as a zero-arg
        callable resolved at span exit (``sync_on=lambda: self.params``)
        — a plain array argument is evaluated at ``with``-entry and can
        only barrier on something that already existed, which is NOT an
        end-of-phase mark for the span's own work.  The fetch-based
        :func:`sync` is used either way (the only honest barrier under
        the relay).  Independent of the epoch lap state: spans may nest
        inside or across :meth:`lap` regions.

        With :attr:`annotate` set, the span body also runs inside a
        ``jax.profiler.TraceAnnotation`` of the same name, so a
        ``--profile-dir`` device trace carries the phases the host
        timeline shows (the merged-timeline lanes and the XLA trace
        line up by name)."""
        ann = annotate(name) if self.annotate else None
        if ann is not None:
            ann.__enter__()
        mono0 = time.monotonic()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync_on is not None:
                sync(sync_on() if callable(sync_on) else sync_on)
            if ann is not None:
                ann.__exit__(None, None, None)
            ms = (time.perf_counter() - t0) * 1e3
            self.spans_ms.setdefault(name, []).append(ms)
            self.timeline.append((name, mono0, ms))

    def note_span(self, name: str, dur_ms: float,
                  mono_end: Optional[float] = None) -> None:
        """Record a span lap measured OUTSIDE :meth:`span` (the epoch
        loop's compile/train/eval laps, the staging pool's per-block
        waits): appends to both the p50/p90 series and the timeline
        records, with the start back-derived from ``mono_end``."""
        if mono_end is None:
            mono_end = time.monotonic()
        self.spans_ms.setdefault(name, []).append(dur_ms)
        self.timeline.append((name, mono_end - dur_ms / 1e3, dur_ms))

    def take_timeline(self) -> List[tuple]:
        """Drain the accumulated timeline span records (the epoch loop
        flushes them into one ``timeline`` event per eval)."""
        out, self.timeline = self.timeline, []
        return out

    def summary(self) -> Dict[str, float]:
        steady = self.laps_ms[self.warmup:] or self.laps_ms
        arr = np.asarray(steady, dtype=np.float64)
        return {
            "laps": len(self.laps_ms),
            "warmup_ms": float(sum(self.laps_ms[:self.warmup])),
            "mean_ms": float(arr.mean()) if arr.size else 0.0,
            "median_ms": float(np.median(arr)) if arr.size else 0.0,
            "p90_ms": float(np.percentile(arr, 90)) if arr.size else 0.0,
            "min_ms": float(arr.min()) if arr.size else 0.0,
        }

    def span_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-phase ``{n, total_ms, p50_ms, p90_ms}`` over every
        recorded span lap (no warmup exclusion: phases that run once —
        first compile — must still show up)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, laps in self.spans_ms.items():
            arr = np.asarray(laps, dtype=np.float64)
            out[name] = {
                "n": int(arr.size),
                "total_ms": float(arr.sum()),
                "p50_ms": float(np.median(arr)),
                "p90_ms": float(np.percentile(arr, 90)),
            }
        return out


class MetricsLog:
    """Append-only training metrics history with JSONL export.  The
    file handle opens lazily on first :meth:`log` (constructing many
    trainers must not accumulate descriptors).  Context-manager use
    guarantees :meth:`close` on exceptions:

    >>> with MetricsLog(path) as log: ...
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.records: List[Dict[str, float]] = []
        self._fh = None

    def log(self, record: Dict[str, Any]) -> None:
        rec = {k: (float(v) if isinstance(v, (int, float, np.floating,
                                              np.integer)) else v)
               for k, v in record.items()}
        # clock tuple (obs/events.py): metrics records merge into the
        # same cross-process timeline as the event streams, so they
        # carry the same (wall, monotonic, host, proc) stamps — never
        # overriding fields the caller measured itself
        from ..obs.events import clock_identity
        rec.setdefault("t", round(time.time(), 3))
        rec.setdefault("mono", round(time.monotonic(), 6))
        for k, v in clock_identity().items():
            rec.setdefault(k, v)
        self.records.append(rec)
        if self.path:
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def last(self) -> Optional[Dict[str, float]]:
        return self.records[-1] if self.records else None
