"""Persistent XLA compilation cache.

The reference pays no compilation cost (hand-written CUDA kernels);
the JAX rebuild's one-time cost is XLA compilation of the jitted step
— 56-122 s at Reddit scale through the remote-compile tunnel, fresh
per process.  JAX's persistent cache keyed on (HLO, compiler version,
device kind) removes that for every process after the first:
measured on v5e through the axon relay, a 2.5 s compile drops to
0.5 s in the next process.  Enabled by default in the CLI and the
benchmark harnesses; library users opt in by calling this before the
first jit.
"""

from __future__ import annotations

import os
from typing import Optional

DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                           "roc_tpu", "xla")


def enable_compile_cache(cache_dir: Optional[str] = None,
                         min_compile_secs: Optional[float] = None
                         ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: $ROC_TPU_CACHE_DIR or ~/.cache/roc_tpu/xla).  Safe to
    call any time before the first compilation; returns the directory
    used, or None when the directory cannot be created (read-only
    HOME, sandboxed CI) — the cache is an optimization, so callers
    must keep working without it.

    ``min_compile_secs`` is the write threshold: programs whose
    compile is faster are NOT persisted.  ``None`` defers to
    $ROC_TPU_CACHE_MIN_SECS, else 1.0 s — which silently skips the
    many small per-block streamed-head programs, so the prewarm
    driver (utils/prewarm.py) and the bench children pass 0.0
    explicitly (TrainConfig.cache_min_compile_secs /
    --cache-min-secs expose it to users)."""
    import jax
    if min_compile_secs is None:
        try:
            min_compile_secs = float(
                os.environ.get("ROC_TPU_CACHE_MIN_SECS", 1.0))
        except ValueError:
            min_compile_secs = 1.0
    d = cache_dir or os.environ.get("ROC_TPU_CACHE_DIR") or DEFAULT_DIR
    try:
        os.makedirs(d, exist_ok=True)
    except OSError as e:
        from ..obs.events import emit
        emit("compile", f"compile cache disabled: cannot create "
             f"{d}: {e}", dir=d)
        return None
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    # The persistent cache object is created once, on the first
    # compilation after it's configured — a later config update alone
    # does NOT re-point an already-initialized cache (observed: the
    # CLI's default-dir cache swallowing a later explicit dir in the
    # same process).  Dropping the instance makes the next compile
    # re-initialize against the directory just configured.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 - older/newer jax layouts
        pass
    return d
