"""Compile-cache pre-warm: pay the compile wall once, off the timed
path.

The program-space auditor (``analysis/programspace.py``) statically
enumerates the EXACT compiled-program set of a config — the same
``candidate_programs`` extraction here drives each candidate through
the AOT path (``jit.lower(*args).compile()``) against the persistent
compile cache (``utils/compile_cache.py``), so every later process
that builds the same trainer starts warm: rebalance, resume, serving,
and the bench probe all skip the first-compile stall that burned every
r01-r05 probe timeout.  Compile-only — nothing executes on the device,
so a prewarm is safe to run while a chip claim is precious.

Warm-vs-cold accounting is file-based: a candidate whose AOT compile
leaves NO new entry in the cache directory was served from the cache
(``compile_warm_hits``); a new entry means it compiled cold and is now
persisted for the next process.  The per-config summary is emitted as
a ``compile`` event (``prewarm=<config>`` field — ``roc_tpu.report``
renders the warm-vs-cold table from it) and returned.

Entry points:

- :func:`prewarm_config` — warm one registered rig config (the
  auditor's exact enumeration; ``python -m roc_tpu.prewarm`` drives
  this, one process per config with ``--jobs``).
- :func:`warm_trainer` — warm a LIVE trainer's candidate programs
  (the bench children call this before their timed phase and record
  ``compile_warm_hits`` / ``compile_cold`` in the stage result).
- :func:`write_warm_state` / :func:`load_warm_state` — the cached
  warm-state artifact (program-key sets per config) the bench probe
  preflight diffs against ``python -m roc_tpu.analysis --json`` so a
  probe refuses to burn chip deadline on a config whose program set
  grew since the cache was warmed.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..obs.events import emit
from .compile_cache import enable_compile_cache

WARM_STATE_NAME = "programspace_warm.json"


def warm_state_path(path: Optional[str] = None) -> str:
    """The warm-state artifact location: explicit > the bench
    artifacts dir (ROC_TPU_BENCH_ARTIFACTS) > the repo's
    ``benchmarks/`` — the same resolution bench.py uses, so the
    prewarm writer and the probe preflight reader agree."""
    if path:
        return path
    art = os.environ.get("ROC_TPU_BENCH_ARTIFACTS") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "benchmarks")
    return os.path.join(art, WARM_STATE_NAME)


def load_warm_state(path: Optional[str] = None) -> Dict[str, Any]:
    """{config: {"programs": n, "keys": [...], "t": iso}} recorded at
    the last prewarm; missing/corrupt file = no cached warm state
    (the preflight then has nothing to guard against)."""
    try:
        with open(warm_state_path(path)) as f:
            db = json.load(f)
        return db if isinstance(db, dict) else {}
    except (OSError, ValueError):
        return {}


def write_warm_state(reports: List[Dict[str, Any]],
                     path: Optional[str] = None) -> str:
    """Merge per-config prewarm reports (carrying ``config`` and
    ``keys``) into the warm-state artifact; returns the path."""
    p = warm_state_path(path)
    state = load_warm_state(p)
    now = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    for rep in reports:
        state[rep["config"]] = {
            "programs": len(rep.get("keys", [])),
            "keys": sorted(rep.get("keys", [])),
            "t": now,
        }
    os.makedirs(os.path.dirname(os.path.abspath(p)), exist_ok=True)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1, sort_keys=True)
    os.replace(tmp, p)
    return p


def _cache_entries(cache_dir: Optional[str]) -> set:
    if not cache_dir:
        return set()
    try:
        return set(os.listdir(cache_dir))
    except OSError:
        return set()


def warm_candidates(cands, cache_dir: Optional[str],
                    config: str = "trainer",
                    verbose: bool = False) -> Dict[str, Any]:
    """AOT-compile every candidate against the persistent cache.
    A candidate whose compile raises is recorded and skipped — a
    corrupt/stale cache entry must degrade to a live compile later,
    never crash the warmer (the cache is an optimization).  Failed
    candidates are excluded from ``keys`` so the warm-state artifact
    never marks a never-warmed program as warmed.  Warm-vs-cold
    attribution is listdir-diff based and exact for a single warmer
    per cache dir; concurrent warmers (``--jobs`` > 1) make it
    best-effort — a sibling's write inside this candidate's window
    counts as cold here (the key sets, the preflight's real guard,
    stay exact).  ``cache_dir=None`` (enable_compile_cache could not
    create the directory — read-only HOME, sandboxed CI) persists
    NOTHING: every compile counts cold, no keys are recorded (the
    next process really does start cold, so the warm state must not
    claim otherwise), and the report carries ``cache_unavailable``
    so the CLI can fail loudly instead of reporting all-warm."""
    from ..obs.compile_watch import program_key_of
    cache_ok = bool(cache_dir) and os.path.isdir(cache_dir)
    if not cache_ok:
        emit("compile", f"prewarm {config}: persistent cache "
             f"UNAVAILABLE (dir={cache_dir!r}) — compiles will not "
             f"persist, nothing is warmed for later processes",
             console=True, prewarm=config, cache_unavailable=True)
    warm = cold = failed = 0
    t_start = time.perf_counter()
    slots: List[Dict[str, Any]] = []
    keys: List[str] = []
    for c in cands:
        before = _cache_entries(cache_dir)
        t0 = time.perf_counter()
        try:
            c.aot()
        except Exception as e:  # noqa: BLE001 - degrade, not die
            failed += 1
            emit("compile", f"prewarm {config}:{c.slot} FAILED: "
                 f"{type(e).__name__}: {e}", console=verbose,
                 prewarm=config, slot=c.slot, error=str(e)[:200])
            continue
        # key recorded only AFTER a successful compile landed in a
        # USABLE cache: a failed (or unpersisted) candidate must show
        # up as GROWTH in the preflight diff (the probe would pay its
        # cold compile), not be masked as already-warm
        if cache_ok:
            keys.append(program_key_of(c.slot, c.args, c.donate))
        dt = time.perf_counter() - t0
        new = _cache_entries(cache_dir) - before
        is_cold = bool(new) or not cache_ok
        cold += is_cold
        warm += not is_cold
        slots.append({"slot": c.slot, "compile_s": round(dt, 3),
                      "cold": is_cold})
        emit("compile", f"prewarm {config}:{c.slot}: {dt:.2f}s "
             f"({'cold' if is_cold else 'warm hit'})",
             console=verbose, prewarm=config, slot=c.slot,
             compile_s=round(dt, 3), cold=is_cold)
    out = {"config": config, "programs": len(list(cands)),
           "compile_warm_hits": warm, "compile_cold": cold,
           "failed": failed,
           "prewarm_s": round(time.perf_counter() - t_start, 2),
           "cache_dir": cache_dir, "slots": slots, "keys": keys}
    if not cache_ok:
        out["cache_unavailable"] = True
    emit("compile", f"prewarm {config}: {out['programs']} programs, "
         f"{warm} warm / {cold} cold"
         + (f" / {failed} failed" if failed else "")
         + f" in {out['prewarm_s']}s",
         prewarm=config, summary=True,
         programs=out["programs"], compile_warm_hits=warm,
         compile_cold=cold, failed=failed,
         prewarm_s=out["prewarm_s"])
    return out


def warm_trainer(tr, cache_dir: Optional[str] = None,
                 name: str = "trainer",
                 verbose: bool = False) -> Dict[str, Any]:
    """Pre-pay a LIVE trainer's whole program set (the bench children
    call this before their timed phase).  Enables the cache at
    min_compile_secs=0.0 — prewarm is driving, so even sub-second
    programs must persist (the 1.0 s default silently skipped the
    small per-block streamed-head programs)."""
    from ..analysis.programspace import candidate_programs
    d = enable_compile_cache(cache_dir, min_compile_secs=0.0)
    return warm_candidates(candidate_programs(tr), d, config=name,
                           verbose=verbose)


def prewarm_config(name: str, dataset=None,
                   cache_dir: Optional[str] = None,
                   verbose: bool = False) -> Optional[Dict[str, Any]]:
    """Warm one registered rig config against the persistent cache:
    builds the rig trainer (tables only — nothing compiles eagerly)
    and AOT-compiles the auditor's exact candidate set.  Returns the
    warm report (with the enumerated ``keys`` for the warm-state
    artifact), or None when the backend cannot host the rig's mesh."""
    import jax

    from ..analysis.programspace import (build_rig_dataset,
                                         build_rig_trainer,
                                         candidate_programs,
                                         rig_configs,
                                         rig_required_devices)
    spec = rig_configs()[name]
    needed = rig_required_devices(spec)
    if needed > len(jax.devices()):
        emit("compile", f"prewarm {name}: skipped (needs "
             f"{needed} devices, have {len(jax.devices())})",
             console=verbose, prewarm=name, skipped=True)
        return None
    d = enable_compile_cache(cache_dir, min_compile_secs=0.0)
    ds = dataset if dataset is not None else build_rig_dataset()
    tr = build_rig_trainer(spec, ds)
    return warm_candidates(candidate_programs(tr), d, config=name,
                           verbose=verbose)
