"""One-launch Pallas TPU kernel for ELL neighbor-sum aggregation.

The reference's defining cost is one cooperative CSR kernel per
partition covering ALL its edges (``scattergather_kernel.cu:79-158``).
This module is the TPU equivalent built exactly to that shape: per
degree bucket, ONE ``pallas_call`` whose grid tiles the whole bucket —
no ``lax.scan`` over edge chunks, no XLA gather on the critical path.

Per grid step ``(i, j)`` covering rows ``[i*BR, (i+1)*BR)`` and widths
``[j*WC, (j+1)*WC)``:

1. the index block ``idx[BR, WC]`` is staged into SMEM by the Pallas
   pipeline (BlockSpec with ``memory_space=SMEM``), so source ids are
   scalar-readable for DMA address computation;
2. each edge's feature row is fetched with an async copy HBM->VMEM into
   an ``NBUF``-deep rotating buffer (DMA ``e+NBUF`` issues while edge
   ``e`` is reduced — the double-buffer pattern, generalized);
3. rows accumulate in fp32 in VMEM and add into the output block,
   which revisits across the ``j`` axis (zeroed at ``j == 0``).

The feature matrix itself never leaves HBM except row-by-row into VMEM,
and the gathered rows are reduced in registers — HBM traffic is the
irreducible ``E*F`` gather plus the output, with no ``[E, F]`` or
``[R, W, F]`` intermediate materialized (the XLA ``ell`` path's
``feats[idx]`` may materialize one depending on fusion).

Whether per-row DMA issue throughput beats XLA's native dynamic-gather
unit is an empirical question — ``benchmarks/micro_agg.py`` measures
both on the real chip and the framework default follows the numbers.

**Measured (TPU v5 lite, 2026-07-29, V=50k E=10M F=256 fp32, median of
10, ~66 ms constant fetch-barrier overhead included in both):**

====================  =========  ========
impl                  wall ms    GB/s
====================  =========  ========
ell (XLA gather)        119.1      86.0
pallas (this kernel)   1006.2      10.2
scan:4096               260.0      39.4
blocked:1024            294.6      34.8
====================  =========  ========

**bf16 limitation (measured 2026-07-30):** with bfloat16 features the
kernel fails Mosaic compilation on v5e (remote-compile INTERNAL error;
the per-row ``[1, F]`` bf16 DMA/accumulate pattern — fp32 compiles and
runs).  The framework never routes bf16 through this kernel by
default (``ell`` wins the race anyway); the micro bench records the
error as data (``measured_baselines.json
neighbor_aggregation_reduced_mixed.impls.pallas``).

The XLA gather path wins by ~18x net of sync overhead and **is the
framework default**.  Two structural reasons, both discovered only by
compiling on real hardware (interpreter mode enforces neither):
(1) HBM memrefs are (8, 128)-tiled, so Mosaic rejects single-row DMAs
outright — every copy must stage an aligned 8-row group, an 8x gather
amplification; (2) DMA issue is serialized through the scalar core,
while XLA's dynamic-gather unit pipelines row fetches in hardware.
This kernel is kept as compiling, tested, honest evidence for that
design decision (``benchmarks/measured_baselines.json`` records the
race), not as a production path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Edges (SMEM index-block elements) per grid step, and the DMA pipeline
# depth.  2048 edges keeps the SMEM block at 8 KiB; 8 outstanding row
# copies hides single-copy latency without exhausting DMA semaphores.
_EDGES_PER_STEP = 2048
_NBUF = 8


def _bucket_kernel(idx_ref, feats_ref, out_ref, buf, sem, *, nbuf: int):
    """One (row-block, width-chunk) tile of a single ELL bucket.

    idx_ref: int32 [BR, WC] in SMEM (source row ids; dummy -> zero row).
    feats_ref: [R_gathered + 1, F] in HBM/ANY (never block-copied).
    out_ref: [BR, F] VMEM output block, revisited over the width axis.
    buf: VMEM [nbuf, 8, F] rotating group buffer; sem: DMA sems [nbuf].

    HBM memrefs are (8, 128)-tiled on TPU, so a single feature row can
    NOT be DMA'd (Mosaic: "slice shape along dimension 0 must be aligned
    to tiling (8)"); each copy therefore stages the aligned 8-row group
    containing the source row and the reduction mask-selects the one row
    — an 8x gather amplification that is this design's intrinsic cost
    (see module docstring for the measured consequence).
    """
    BR, WC = idx_ref.shape
    F = out_ref.shape[1]
    total_rows = feats_ref.shape[0]
    j = pl.program_id(1)
    total = BR * WC

    def group_base(e):
        # aligned 8-row group start; the wrapper pads feats to a
        # multiple of 8 rows, so this is always in-bounds AND Mosaic
        # can prove tiling divisibility (a min-clamp defeats the prover)
        gid = idx_ref[e // WC, e % WC]
        return (gid // 8) * 8

    def dma(e, slot):
        return pltpu.make_async_copy(
            feats_ref.at[pl.ds(group_base(e), 8), :],
            buf.at[slot],
            sem.at[slot])

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # warm the pipeline
    for k in range(min(nbuf, WC)):  # static unroll; nbuf, WC static
        dma(k, k % nbuf).start()

    lane = lax.broadcasted_iota(jnp.int32, (8, 1), 0)

    def row_body(r, _):
        def w_body(w, acc):
            e = r * WC + w
            slot = lax.rem(e, nbuf)
            dma(e, slot).wait()
            gid = idx_ref[e // WC, e % WC]
            sub = gid - group_base(e)
            rows = buf[slot].astype(jnp.float32)
            acc = acc + jnp.sum(
                jnp.where(lane == sub, rows, 0.0), axis=0, keepdims=True)
            nxt = e + nbuf

            @pl.when(nxt < total)
            def _():
                dma(nxt, slot).start()

            return acc

        acc = lax.fori_loop(0, WC, w_body, jnp.zeros((1, F), jnp.float32),
                            unroll=False)
        out_ref[pl.ds(r, 1), :] = (
            out_ref[pl.ds(r, 1), :] + acc.astype(out_ref.dtype))
        return 0

    lax.fori_loop(0, BR, row_body, 0, unroll=False)


def _tile_shape(rows: int, width: int) -> Tuple[int, int]:
    """(BR, WC): rows x width-chunk per grid step.  Mosaic requires the
    last two block dims to be divisible by (8, 128) or equal to the
    whole (padded) array dims — interpreter mode does not enforce this,
    the real compiler does (measured on v5e) — so BR is rounded up to a
    multiple of 8 and WC is either the full width or 128-aligned."""
    wc = min(width, _EDGES_PER_STEP)
    if wc < width:
        wc = max(128, (wc // 128) * 128)
    br = max(1, min(256, _EDGES_PER_STEP // wc))
    br = -(-br // 8) * 8
    return br, wc


@functools.partial(jax.jit,
                   static_argnames=("num_rows", "interpret"))
def ell_aggregate_pallas(feats: jax.Array, ell_idx, ell_row_pos: jax.Array,
                         num_rows: int,
                         interpret: bool = False) -> jax.Array:
    """Drop-in for :func:`roc_tpu.ops.aggregate.aggregate_ell` backed by
    the one-launch-per-bucket Pallas kernel.

    feats: [R_gathered + 1, F] with trailing zero row (dummy target).
    ell_idx: tuple of int32 [rows_b, width_b] bucket index tables.
    ell_row_pos: int32 [num_rows] inverse permutation (core/ell.py).
    """
    F = feats.shape[1]
    dummy = feats.shape[0] - 1
    # pad rows to a multiple of 8 so every aligned 8-row DMA group is
    # in-bounds (HBM tiling; see _bucket_kernel docstring)
    Rg = feats.shape[0]
    Rg8 = -(-Rg // 8) * 8
    if Rg8 != Rg:
        feats = jnp.pad(feats, ((0, Rg8 - Rg), (0, 0)))
    outs = []
    for idx in ell_idx:
        R, W = idx.shape
        BR, WC = _tile_shape(R, W)
        Rp = -(-R // BR) * BR
        Wp = -(-W // WC) * WC
        if Rp != R or Wp != W:
            idx = jnp.pad(idx, ((0, Rp - R), (0, Wp - W)),
                          constant_values=dummy)
        grid = (Rp // BR, Wp // WC)
        out = pl.pallas_call(
            functools.partial(_bucket_kernel, nbuf=_NBUF),
            grid=grid,
            in_specs=[
                pl.BlockSpec((BR, WC), lambda i, j: (i, j),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec((BR, F), lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((Rp, F), feats.dtype),
            scratch_shapes=[
                pltpu.VMEM((_NBUF, 8, F), feats.dtype),
                pltpu.SemaphoreType.DMA((_NBUF,)),
            ],
            interpret=interpret,
        )(idx, feats)
        outs.append(out[:R])
    zero = jnp.zeros((1, F), dtype=feats.dtype)
    cat = jnp.concatenate(outs + [zero], axis=0)
    return cat[ell_row_pos]
