"""Pallas TPU kernel for the CSR neighbor-sum hot loop.

The reference's defining cost is ``aggre_coop_kernel``
(``scattergather_kernel.cu:20-76``): a cub-BlockScan cooperative CSR
sum-aggregation over destination-sorted edges.  This module is the
TPU-native equivalent: a fused segmented reduction over edge chunks,
one chunk per VMEM-resident kernel invocation, driven by the same
write-once window + carry-record scheme as
:func:`roc_tpu.ops.aggregate.aggregate_scan`.

Per chunk of ``C`` sorted edges the kernel fuses, in one VMEM pass:

1. local destination ids from the chunk's first row,
2. the segmented sum as a *one-hot MXU contraction*
   ``onehot(local)^T @ g`` — Mosaic has no VMEM vector-gather, so the
   selection matmul is the TPU's native scatter-free reduction,
3. masking of the chunk's last row into a carry record (emitted for a
   post-scan scatter-add, so output windows are written exactly once).

The feature gather itself stays in XLA (``feats[src]`` — the TPU's
dynamic-gather path, the irreducible cost: ~5.3 ns/row measured on
v5e at V=50k E=10M F=256, benchmarks/measured_baselines.json);
everything after it lands in this kernel.
VMEM working set is O(C * (C + F)), independent of E.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _seg_reduce_kernel(dst_ref, g_ref, out_ref, carry_ref):
    """One edge chunk: segmented sum of gathered rows ``g`` by sorted
    local destination, emitting the window block + last-row carry."""
    C = dst_ref.shape[1]
    F = g_ref.shape[1]
    dst = dst_ref[0, :]                               # [C] int32
    r0 = dst_ref[0, 0]
    local = dst - r0                                  # [C] in [0, C)
    pos = dst_ref[0, C - 1] - r0                      # last local row

    # Scatter-free segmented reduction: sel[e, j] = (local[e] == j);
    # sel^T @ g on the MXU with fp32 accumulation.
    jj = lax.broadcasted_iota(jnp.int32, (C, C), 1)
    sel = (local[:, None] == jj).astype(jnp.float32)  # [C(e), C(j)]
    g = g_ref[:].astype(jnp.float32)                  # [C, F]
    L = lax.dot_general(sel, g, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)  # [C, F]

    carry_ref[0, :] = lax.dynamic_slice(L, (pos, 0), (1, F))[0].astype(
        carry_ref.dtype)
    rows = lax.broadcasted_iota(jnp.int32, (C, 1), 0)
    out_ref[:] = jnp.where(rows == pos, 0.0, L).astype(out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("num_rows", "chunk", "interpret"))
def csr_spmm_pallas(feats: jax.Array, edge_src: jax.Array,
                    edge_dst: jax.Array, num_rows: int,
                    chunk: int = 512,
                    interpret: bool = False) -> jax.Array:
    """``out[dst] = sum feats[src]`` over dst-sorted padded edges.

    Same contract as :func:`roc_tpu.ops.aggregate.aggregate_blocked`:
    ``feats`` is ``[R+1, F]`` with a trailing zero dummy row, edges are
    padded to a ``chunk`` multiple, every destination has degree >= 1
    over the full edge list (so a chunk of C edges spans <= C rows).
    """
    E = edge_src.shape[0]
    F = feats.shape[1]
    assert E % chunk == 0, "pad edges to a chunk multiple"
    C = chunk
    n_chunks = E // C
    src_c = edge_src.reshape(n_chunks, C)
    dst_c = edge_dst.reshape(n_chunks, 1, C)

    kernel = pl.pallas_call(
        _seg_reduce_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((C, F), feats.dtype),
            jax.ShapeDtypeStruct((1, F), feats.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )

    out0 = jnp.zeros((num_rows + C, F), dtype=feats.dtype)

    def body(out, inputs):
        src, dst = inputs
        g = feats[src]                                # XLA gather
        window, carry = kernel(dst, g)
        out = lax.dynamic_update_slice(out, window, (dst[0, 0], 0))
        return out, (dst[0, C - 1], carry[0])

    out, (rows, vecs) = lax.scan(body, out0, (src_c, dst_c))
    out = out.at[rows].add(vecs)
    return out[:num_rows]
