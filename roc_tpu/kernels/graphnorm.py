"""Pallas TPU kernels for in-degree normalization (GraphNorm) and the
fused normalize-aggregate-activate chain.

Reference: ``graphnorm_kernel.cu:45-55`` computes
``out[v, :] = in[v, :] / sqrt(indegree(v))`` from CSR row pointers;
applied before and after the neighbor sum it yields the symmetric GCN
normalization ``D^-1/2 A D^-1/2``.  The op is its own linear transpose,
so the reference reuses the forward kernel in backward
(``graphnorm_kernel.cu:127-136``) — here that falls out of autodiff
since the op is a broadcast multiply by a constant vector.

On TPU the degrees are static per graph, so the kernel is a tiled
broadcast scale: rows stream through VMEM in (block, lane-aligned)
tiles, ``rsqrt`` runs on the VPU.  Zero-degree (padding) rows map to
zero output, matching :func:`roc_tpu.ops.norm.inv_sqrt_degree`.

**Fused epilogue** (:func:`scale_act_pallas`): the post-aggregation
half of the GCN sandwich — ``act(y * d_dst)`` — in ONE tiled VMEM
pass instead of the unfused chain's separate norm and relu ops.
:func:`fused_ell_aggregate_pallas` composes the hand-written route
end to end: pre-scale kernel -> one-launch ELL DMA aggregation
(kernels/ell_spmm.py) -> fused scale(+activate) epilogue, so
``aggr_impl='pallas'`` under ``aggr_fuse`` never leaves hand-written
kernels for the whole normalize-aggregate-activate chain.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _norm_kernel(deg_ref, x_ref, out_ref):
    deg = deg_ref[:].astype(jnp.float32)                     # [B, 1]
    scale = jnp.where(deg > 0,
                      jax.lax.rsqrt(jnp.maximum(deg, 1.0)), 0.0)
    out_ref[:] = (x_ref[:].astype(jnp.float32) * scale).astype(
        out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def indegree_norm_pallas(x: jax.Array, in_degree: jax.Array,
                         block: int = 1024,
                         interpret: bool = False) -> jax.Array:
    """``x * rsqrt(max(in_degree, 1))[:, None]`` with rows tiled through
    VMEM.  ``x``: [V, F]; ``in_degree``: int32 [V].  ``interpret``
    runs the interpreter (CPU tests — jax dropped the global
    force_tpu_interpret_mode switch)."""
    V, F = x.shape
    B = min(block, V)
    Vp = pl.cdiv(V, B) * B
    if Vp != V:
        x = jnp.pad(x, ((0, Vp - V), (0, 0)))
        in_degree = jnp.pad(in_degree, (0, Vp - V))
    deg2d = in_degree.reshape(Vp, 1)
    out = pl.pallas_call(
        _norm_kernel,
        grid=(Vp // B,),
        in_specs=[
            pl.BlockSpec((B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((B, F), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Vp, F), x.dtype),
        interpret=interpret,
    )(deg2d, x)
    return out[:V]


def _scale_act_kernel(scale_ref, x_ref, out_ref, *, act: str):
    s = scale_ref[:].astype(jnp.float32)                     # [B, 1]
    y = x_ref[:].astype(jnp.float32) * s
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    out_ref[:] = y.astype(out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("act", "block", "interpret"))
def scale_act_pallas(x: jax.Array, scale: jax.Array,
                     act: str = "none", block: int = 1024,
                     interpret: bool = False) -> jax.Array:
    """Fused epilogue: ``act(x * scale[:, None])`` in one tiled VMEM
    pass — the post-norm (a PRECOMPUTED fp32 ``d = deg^-1/2`` vector)
    and the activation that the unfused chain spends two full [V, F]
    HBM round trips on.  ``act``: 'none' | 'relu'."""
    if act not in ("none", "relu"):
        raise ValueError(f"unknown act {act!r}; expected 'none'|'relu'")
    V, F = x.shape
    B = min(block, V)
    Vp = pl.cdiv(V, B) * B
    if Vp != V:
        x = jnp.pad(x, ((0, Vp - V), (0, 0)))
        scale = jnp.pad(scale, (0, Vp - V))
    s2d = scale.astype(jnp.float32).reshape(Vp, 1)
    out = pl.pallas_call(
        functools.partial(_scale_act_kernel, act=act),
        grid=(Vp // B,),
        in_specs=[
            pl.BlockSpec((B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((B, F), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Vp, F), x.dtype),
        interpret=interpret,
    )(s2d, x)
    return out[:V]


def fused_ell_aggregate_pallas(full: jax.Array, ell_idx,
                               ell_row_pos: jax.Array, num_rows: int,
                               d_dst: jax.Array, act: str = "none",
                               interpret: bool = False) -> jax.Array:
    """Aggregate-and-scale tail of the hand-written fused chain:
    the one-launch ELL DMA aggregation (kernels/ell_spmm.py) followed
    by the :func:`scale_act_pallas` epilogue ``act(y * d_dst)``.

    ``full`` must already carry the PRE-scaled features (the caller
    runs :func:`indegree_norm_pallas` on the local rows before the
    halo gather — under shard_map the pre-scale must happen in local
    coordinates).  ``d_dst``: fp32 [num_rows] inv-sqrt degrees of the
    output rows.  With ``act='none'`` this is the exact linear
    operator ``D^-1/2 A D^-1/2`` the symmetric-vjp fused aggregation
    wraps; ``act='relu'`` is the full forward-only chain the
    benchmarks race."""
    from .ell_spmm import ell_aggregate_pallas
    y = ell_aggregate_pallas(full, ell_idx, ell_row_pos, num_rows,
                             interpret=interpret)
    return scale_act_pallas(y, d_dst, act=act, interpret=interpret)
