"""Pallas TPU kernel for in-degree normalization (GraphNorm).

Reference: ``graphnorm_kernel.cu:45-55`` computes
``out[v, :] = in[v, :] / sqrt(indegree(v))`` from CSR row pointers;
applied before and after the neighbor sum it yields the symmetric GCN
normalization ``D^-1/2 A D^-1/2``.  The op is its own linear transpose,
so the reference reuses the forward kernel in backward
(``graphnorm_kernel.cu:127-136``) — here that falls out of autodiff
since the op is a broadcast multiply by a constant vector.

On TPU the degrees are static per graph, so the kernel is a tiled
broadcast scale: rows stream through VMEM in (block, lane-aligned)
tiles, ``rsqrt`` runs on the VPU.  Zero-degree (padding) rows map to
zero output, matching :func:`roc_tpu.ops.norm.inv_sqrt_degree`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _norm_kernel(deg_ref, x_ref, out_ref):
    deg = deg_ref[:].astype(jnp.float32)                     # [B, 1]
    scale = jnp.where(deg > 0,
                      jax.lax.rsqrt(jnp.maximum(deg, 1.0)), 0.0)
    out_ref[:] = (x_ref[:].astype(jnp.float32) * scale).astype(
        out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block",))
def indegree_norm_pallas(x: jax.Array, in_degree: jax.Array,
                         block: int = 1024) -> jax.Array:
    """``x * rsqrt(max(in_degree, 1))[:, None]`` with rows tiled through
    VMEM.  ``x``: [V, F]; ``in_degree``: int32 [V]."""
    V, F = x.shape
    B = min(block, V)
    Vp = pl.cdiv(V, B) * B
    if Vp != V:
        x = jnp.pad(x, ((0, Vp - V), (0, 0)))
        in_degree = jnp.pad(in_degree, (0, Vp - V))
    deg2d = in_degree.reshape(Vp, 1)
    out = pl.pallas_call(
        _norm_kernel,
        grid=(Vp // B,),
        in_specs=[
            pl.BlockSpec((B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((B, F), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Vp, F), x.dtype),
    )(deg2d, x)
    return out[:V]
