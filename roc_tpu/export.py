"""``python -m roc_tpu.export`` — thin entry point for the serve
export CLI (the implementation lives in ``roc_tpu/serve/export.py``,
same packaging convention as ``roc_tpu.timeline`` / ``roc_tpu.
sentinel``)."""

from .serve.export import main  # noqa: F401

if __name__ == "__main__":
    import sys
    sys.exit(main())
