"""roc-tpu: TPU-native distributed full-graph GNN training.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the
Legion+CUDA reference system ROC (makemebitter/ROC) — see SURVEY.md.
"""

__version__ = "0.1.0"

from .core.graph import (Dataset, Graph, add_self_edges, from_edge_list,
                         load_dataset, load_lux, save_lux,
                         synthetic_dataset, synthetic_graph,
                         MASK_NONE, MASK_TRAIN, MASK_VAL, MASK_TEST)
from .core.partition import (PartitionedGraph, edge_balanced_bounds,
                             padded_edge_list, partition_bounds,
                             partition_graph)
from .core.costmodel import (PartitionCostModel, cost_balanced_bounds,
                             partition_static_stats)
from .core.ell import EllTable, ell_from_graph, ell_from_padded_parts
from .models.builder import (AGGR_AVG, AGGR_MAX, AGGR_SUM, GraphContext,
                             Model)
from .models.gcn import build_gcn
from .models.sage import build_sage
from .models.gin import build_gin
from .models.gat import build_gat
from .models.sgc import build_sgc
from .models.appnp import build_appnp
from .models.gcn2 import build_gcn2
from .train.optimizer import (AdamConfig, AdamState, adam_init,
                              adam_update, decayed_lr)
from .utils.checkpoint import (checkpoint_trainer, load_checkpoint,
                               restore_trainer, save_checkpoint)
from .obs import Heartbeat, configure as configure_events, emit
