"""Rules over ClosedJaxprs of the trainers' step functions.

Each lint *unit* is one traced program (train step, eval step, the
recorded-op model graph) plus the static context a rule needs to tell
intended from unintended: the configured compute dtype, the
dataset's [V, F] scale, the halo mode, donation thresholds.  Rules
walk the whole nesting (pjit / shard_map / custom_vjp / scan bodies)
— an anti-pattern inside a remat body is still an anti-pattern.

The thresholds are *scale-relative*, not absolute: "[V, F]-scale"
means the full per-device activation footprint, so the same rules
bite on a 256-node CI fixture and a 233M-edge production graph.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .findings import Finding

# int32 overflow hazard threshold (rule jaxpr-int32-overflow)
_INT32_LIMIT = 2 ** 31

# host-callback primitive names across jax versions
_CALLBACK_PRIMS = ("debug_callback", "pure_callback", "io_callback",
                   "debug_print", "outside_call", "host_callback")

_COLLECTIVE_GATHERS = ("all_gather", "all_gather_invariant",
                       "all_to_all")


@dataclass
class JaxprUnit:
    """One traced program under lint.

    ``jaxpr`` is a ClosedJaxpr (``jax.make_jaxpr(fn)(*args)``).
    ``compute_dtype`` is the dtype the config says activations run in
    (the bf16-upcast rule only arms when it is 'bfloat16');
    ``vf_elems`` the full activation element count (V*F) the
    scale-relative rules compare against; ``donate_min_bytes`` the
    buffer size past which a non-donated update-shaped argument is
    worth flagging (the driver passes the largest parameter leaf);
    ``index_bound`` the conservative max value of integer inputs
    (node ids — defaults to V)."""

    name: str
    jaxpr: Any
    compute_dtype: str = "float32"
    num_nodes: int = 0
    vf_elems: int = 0
    halo: str = "gather"
    donate_min_bytes: int = 1 << 20
    index_bound: Optional[int] = None
    # mesh size for shard_map'd units: avals inside the body are
    # block-LOCAL, so vf_elems must be the PER-DEVICE V/P * F there,
    # and the sanctioned whole-region gather is mesh_parts * vf_elems
    mesh_parts: int = 1
    detail: Dict[str, Any] = field(default_factory=dict)

    @property
    def unit(self) -> str:
        return f"jaxpr:{self.name}"


def _inner_jaxprs(eqn) -> Iterator[Any]:
    """Jaxprs nested in an eqn's params (pjit/shard_map/custom_vjp/
    scan/remat bodies), whatever the param key."""
    for v in eqn.params.values():
        for item in (v if isinstance(v, (tuple, list)) else (v,)):
            if hasattr(item, "jaxpr") and hasattr(
                    getattr(item, "jaxpr"), "eqns"):
                yield item.jaxpr          # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item                # raw Jaxpr


def iter_eqns(closed_jaxpr) -> Iterator[Any]:
    """Every eqn in the program, depth-first across all nesting."""
    stack = [closed_jaxpr.jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield eqn
            stack.extend(_inner_jaxprs(eqn))


def _aval(v):
    return getattr(v, "aval", None)


def _elems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _shape_str(aval) -> str:
    return (f"{getattr(aval, 'dtype', '?')}"
            f"{list(getattr(aval, 'shape', ()))}")


# --------------------------------------------------------------- rules

def check_f32_upcast(u: JaxprUnit) -> List[Finding]:
    """[jaxpr-f32-upcast] ``convert_element_type`` bf16 -> f32 of an
    activation-scale tensor inside a bf16-configured path: the mixed-
    precision contract is that features/activations stay bf16 through
    the sandwich — a [V, F]-scale upcast silently doubles the HBM
    traffic the mode exists to halve.  Class-width tensors (the fp32
    loss/softmax reduction, [V, C] with C << F) stay sanctioned by the
    scale threshold."""
    out: List[Finding] = []
    if u.compute_dtype != "bfloat16" or not u.vf_elems:
        return out
    for eqn in iter_eqns(u.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = _aval(eqn.invars[0])
        dst = _aval(eqn.outvars[0])
        if (src is None or dst is None
                or str(src.dtype) != "bfloat16"
                or str(dst.dtype) != "float32"):
            continue
        if _elems(src) >= u.vf_elems:
            out.append(Finding(
                "jaxpr-f32-upcast", u.unit,
                f"bf16 -> f32 upcast of activation-scale tensor "
                f"{_shape_str(src)} (>= V*F = {u.vf_elems} elems) in "
                f"a bf16-configured path",
                key=f"upcast|{_shape_str(src)}"))
    return out


def check_host_callback(u: JaxprUnit) -> List[Finding]:
    """[jaxpr-host-callback] host callbacks / debug prints under jit:
    each one is a device->host round trip per step, serializing the
    dispatch pipeline (and on multi-host rigs, desynchronizing
    SPMD programs)."""
    out: List[Finding] = []
    for eqn in iter_eqns(u.jaxpr):
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS or name.endswith("_callback"):
            out.append(Finding(
                "jaxpr-host-callback", u.unit,
                f"host callback primitive '{name}' inside the jitted "
                f"step (per-step device->host round trip)",
                key=f"callback|{name}"))
    return out


def check_non_donated(u: JaxprUnit) -> List[Finding]:
    """[jaxpr-non-donated] a large argument whose aval matches an
    output aval but is not donated: params/opt-state-sized buffers
    passed undonated double their HBM residency for the whole step
    (XLA must keep the input alive while writing the update).

    Only the DISPATCH-BOUNDARY pjit is judged — the single top-level
    pjit eqn of a traced jitted callable.  Donation is a caller-side
    contract at that boundary; inner library pjits are inlined by XLA,
    which reuses their buffers without any donate_argnums.

    Value-and-grad recognition: jax's ``value_and_grad`` convention
    puts the scalar value FIRST and the gradients after it — a
    grad-shaped output of such a jaxpr is a *cotangent* of its primal
    argument, not an update of it, and the caller by construction
    still needs the primal afterwards (the optimizer apply consumes
    params AND grads), so donation is not the fix and flagging it was
    the rule's one known false positive (the retired ``tail_grad``
    baseline entry).  An update-style step (params first, or no
    leading scalar) is judged exactly as before.

    A scalar PARAM that happens to flatten first (e.g. a learned-eps
    GIN) must not disarm the rule for update steps: the echo guard
    below refuses the exemption when the first two output avals
    mirror the first two input avals in order — an update step echoes
    its input prefix (params head INCLUDING the scalar), while
    value_and_grad's leading scalar is the loss, whose successor is
    the first *cotangent* and so tracks the primal's leaf 0, not
    leaf 1.

    Known limit of the convention heuristic: a hand-written update
    step returning ``(loss, new_params, new_opt_state)`` — scalar
    FIRST — would be exempted too, since avals alone cannot separate
    cotangents from updated buffers (adam state is param-shaped, so
    even cross-arg matching can't).  This repo's steps return loss
    LAST (the flagged surface), and every step slot is a fixed,
    linted unit in driver.py — a new scalar-first update slot should
    keep that convention or donate explicitly."""
    out: List[Finding] = []
    top = [e for e in u.jaxpr.jaxpr.eqns
           if e.primitive.name == "pjit"]
    if len(top) != 1 or len(u.jaxpr.jaxpr.eqns) != 1:
        return out
    for eqn in top:
        donated = eqn.params.get("donated_invars")
        if donated is None:
            continue
        out_sigs = []
        for v in eqn.outvars:
            a = _aval(v)
            out_sigs.append((tuple(a.shape), str(a.dtype))
                            if a is not None else None)
        in_sigs = []
        for v in eqn.invars[:2]:
            a = _aval(v)
            in_sigs.append((tuple(a.shape), str(a.dtype))
                           if a is not None else None)
        # an output prefix that mirrors the input prefix in ORDER is
        # an update-step echo, not (value, grads...) — see docstring
        echo_prefix = (len(out_sigs) >= 2 and len(in_sigs) == 2
                       and None not in in_sigs
                       and out_sigs[0] == in_sigs[0]
                       and out_sigs[1] == in_sigs[1])
        value_and_grad_like = (
            bool(out_sigs) and out_sigs[0] is not None
            and out_sigs[0][0] == () and "float" in out_sigs[0][1]
            and len(out_sigs) > 1 and not echo_prefix)
        for pos, (var, don) in enumerate(zip(eqn.invars, donated)):
            if don:
                continue
            a = _aval(var)
            if a is None:
                continue
            sig = (tuple(a.shape), str(a.dtype))
            nbytes = _elems(a) * getattr(a.dtype, "itemsize", 4)
            matches = [i for i, s in enumerate(out_sigs) if s == sig]
            if not matches or nbytes < u.donate_min_bytes:
                continue
            if value_and_grad_like and all(i > 0 for i in matches):
                continue    # cotangents of a (value, grads...) jaxpr
            out.append(Finding(
                "jaxpr-non-donated", u.unit,
                f"arg {pos} ({_shape_str(a)}, {nbytes} B) matches "
                f"an output aval but is not donated — its HBM "
                f"residency is doubled across the step; add it to "
                f"donate_argnums",
                key=f"nondonated|{pos}|{_shape_str(a)}"))
    return out


def check_collective_materialize(u: JaxprUnit) -> List[Finding]:
    """[jaxpr-collective-materialize] cross-shard materialization of
    activation-scale tensors: a psum whose operand is [V, F]-scale
    (the symmetric-vjp design exists precisely so gradients re-run the
    forward gather instead), any all-gather under halo='ring' (the
    ring's whole point is never materializing [V, F] per device), or
    a gather landing MORE than the designed whole-region [V, F]."""
    out: List[Finding] = []
    if not u.vf_elems:
        return out
    for eqn in iter_eqns(u.jaxpr):
        name = eqn.primitive.name
        if name == "psum":
            for var in eqn.invars:
                a = _aval(var)
                if a is not None and _elems(a) >= u.vf_elems:
                    out.append(Finding(
                        "jaxpr-collective-materialize", u.unit,
                        f"psum of activation-scale tensor "
                        f"{_shape_str(a)} (>= V*F = {u.vf_elems}) — "
                        f"an implicit cross-shard materialization; "
                        f"the symmetric custom-vjp aggregation path "
                        f"avoids this",
                        key=f"psum|{_shape_str(a)}"))
        elif name in _COLLECTIVE_GATHERS:
            a = _aval(eqn.outvars[0])
            if a is None:
                continue
            n = _elems(a)
            whole_region = u.vf_elems * max(u.mesh_parts, 1)
            if u.halo == "ring" and n >= u.vf_elems:
                out.append(Finding(
                    "jaxpr-collective-materialize", u.unit,
                    f"{name} materializes {_shape_str(a)} under "
                    f"halo='ring' — the ring exists to keep per-device "
                    f"peak at O(V/P * F)",
                    key=f"ring-gather|{name}|{_shape_str(a)}"))
            elif n >= 2 * whole_region:
                out.append(Finding(
                    "jaxpr-collective-materialize", u.unit,
                    f"{name} materializes {_shape_str(a)} — larger "
                    f"than the designed whole-region [V, F] gather "
                    f"({whole_region} elems)",
                    key=f"gather|{name}|{_shape_str(a)}"))
    return out


def _int_limit(dtype) -> Optional[int]:
    s = str(dtype)
    if s == "int32":
        return 2 ** 31
    if s == "uint32":
        return 2 ** 32
    if s == "int16":
        return 2 ** 15
    if s == "uint16":
        return 2 ** 16
    return None     # int64/unknown: not a hazard we track


def check_int32_overflow(u: JaxprUnit) -> List[Finding]:
    """[jaxpr-int32-overflow] index arithmetic whose STATIC bound
    exceeds the result dtype's range: a conservative max-abs-value
    propagation over the integer eqns (literals exact, iota = size-1,
    integer inputs bounded by ``index_bound`` — node ids can't exceed
    V).  At billion-edge scale ``row * F + col`` flattening in int32
    silently wraps; this catches it at trace time, plus int64->int32
    truncations of already-overflowing bounds."""
    out: List[Finding] = []
    bound_default = u.index_bound if u.index_bound is not None \
        else max(u.num_nodes, 1)

    def run(jaxpr, bounds: Dict[Any, int]) -> None:
        def get(v) -> Optional[int]:
            if hasattr(v, "val"):         # Literal
                try:
                    return int(abs(int(v.val)))
                except (TypeError, ValueError, OverflowError):
                    return None
            return bounds.get(v)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "pjit" or _is_container(eqn):
                for inner in _inner_jaxprs(eqn):
                    inner_bounds: Dict[Any, int] = {}
                    for iv, ov in zip(getattr(inner, "invars", ()),
                                      eqn.invars):
                        b = get(ov)
                        if b is not None:
                            inner_bounds[iv] = b
                    seed_int_invars(inner, inner_bounds)
                    run(inner, inner_bounds)
                continue
            if not eqn.outvars:
                continue
            oav = _aval(eqn.outvars[0])
            odt = getattr(oav, "dtype", None)
            is_int = odt is not None and "int" in str(odt)
            ins = [get(v) for v in eqn.invars]
            res: Optional[int] = None
            arith = False
            if name == "iota":
                dim = eqn.params.get("dimension", 0)
                shape = eqn.params.get("shape", (1,))
                res = max(int(shape[dim]) - 1, 0)
            elif name in ("mul", "dot_general") and is_int:
                arith = True
                if None not in ins[:2]:
                    res = ins[0] * ins[1]
                    if name == "dot_general":
                        k = _elems(_aval(eqn.invars[0])) or 1
                        res *= k
            elif name in ("add", "sub") and is_int:
                arith = True
                if None not in ins[:2]:
                    res = ins[0] + ins[1]
            elif name == "reduce_sum" and is_int:
                arith = True
                if ins[0] is not None:
                    n = _elems(_aval(eqn.invars[0]))
                    res = ins[0] * max(n, 1)
            elif name in ("max", "min", "concatenate"):
                known = [b for b in ins if b is not None]
                res = max(known) if known else None
            elif name in ("broadcast_in_dim", "reshape", "squeeze",
                          "transpose", "expand_dims", "slice",
                          "dynamic_slice", "rev", "copy",
                          "stop_gradient", "gather", "take"):
                res = ins[0]
            elif name == "convert_element_type":
                res = ins[0]
                lim = _int_limit(odt) if is_int else None
                if res is not None and lim and res >= lim:
                    out.append(Finding(
                        "jaxpr-int32-overflow", u.unit,
                        f"narrowing convert to {odt} truncates: "
                        f"static bound {res} >= {lim}",
                        key=f"narrow|{odt}|{_shape_str(oav)}"))
            if arith and res is not None:
                lim = _int_limit(odt)
                if lim and res >= lim:
                    out.append(Finding(
                        "jaxpr-int32-overflow", u.unit,
                        f"{name} on {odt} has static bound {res} >= "
                        f"{lim} — index arithmetic overflows; compute "
                        f"in int64 (or rescale) before narrowing",
                        key=f"overflow|{name}|{odt}|{_shape_str(oav)}"))
            if res is not None:
                for ov in eqn.outvars:
                    bounds[ov] = res

    def seed_int_invars(jaxpr, bounds) -> None:
        for v in getattr(jaxpr, "invars", ()):
            a = _aval(v)
            if v not in bounds and a is not None \
                    and "int" in str(getattr(a, "dtype", "")):
                bounds[v] = bound_default

    def _is_container(eqn) -> bool:
        return any(True for _ in _inner_jaxprs(eqn))

    top = u.jaxpr.jaxpr
    bounds: Dict[Any, int] = {}
    seed_int_invars(top, bounds)
    run(top, bounds)
    return out


JAXPR_RULES = {
    "jaxpr-f32-upcast": check_f32_upcast,
    "jaxpr-host-callback": check_host_callback,
    "jaxpr-non-donated": check_non_donated,
    "jaxpr-collective-materialize": check_collective_materialize,
    "jaxpr-int32-overflow": check_int32_overflow,
}


def run_jaxpr_lint(units: List[JaxprUnit],
                   select: Optional[List[str]] = None
                   ) -> List[Finding]:
    findings: List[Finding] = []
    for unit in units:
        for name, rule in JAXPR_RULES.items():
            if select is not None and name not in select:
                continue
            findings.extend(rule(unit))
    return findings
