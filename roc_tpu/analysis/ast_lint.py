"""Rule-driven AST lint over the source tree.

Generalizes the original ``scripts/lint_prints.sh`` heredoc (whose
stdout-print rule migrated here verbatim) into a registry of rules,
each scoped to the modules whose invariants it guards.  Suppression is
per-line and self-documenting: a trailing ``# roc-lint: ok`` (any
rule) or ``# roc-lint: ok=rule-a,rule-b`` on the flagged line — or the
line above it — accepts the finding at the call site, with the comment
text carrying the why.  jax-free by design: the AST layer must run in
milliseconds with no backend.

Adding a rule: subclass :class:`AstRule`, set ``name``/``why``,
implement ``select`` (which repo-relative paths it lints) and
``check`` (yield :class:`Finding`), and append an instance to
:data:`RULES`.  Give every finding a line number and a stable ``key``
if the message embeds location-dependent text.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable, List, Optional

from .findings import Finding


def pragma_ok(lines: List[str], lineno: Optional[int],
              rule: str) -> bool:
    """True when the flagged line (or the line above — decorators,
    wrapped calls) carries a ``# roc-lint: ok`` pragma covering
    ``rule``."""
    if lineno is None:
        return False
    for ln in (lineno, lineno - 1):
        if not 1 <= ln <= len(lines):
            continue
        text = lines[ln - 1]
        mark = "roc-lint: ok"
        pos = text.find(mark)
        if pos < 0:
            continue
        rest = text[pos + len(mark):]
        if not rest.startswith("="):
            return True          # bare pragma: every rule
        names = rest[1:].split()[0] if rest[1:].split() else ""
        if rule in [r.strip() for r in names.split(",")]:
            return True
    return False


class AstRule:
    name = "abstract"
    why = ""

    def select(self, relpath: str) -> bool:
        raise NotImplementedError

    def check(self, tree: ast.AST, relpath: str) -> Iterable[Finding]:
        raise NotImplementedError


def _is_name(node: ast.AST, name: str) -> bool:
    return isinstance(node, ast.Name) and node.id == name


def _is_attr(node: ast.AST, attr: str,
             base: Optional[str] = None) -> bool:
    """``<base>.<attr>`` (any base when ``base`` is None)."""
    return (isinstance(node, ast.Attribute) and node.attr == attr
            and (base is None or _is_name(node.value, base)))


class StdoutPrintRule(AstRule):
    """Bare ``print()`` to stdout — stdout belongs to the metrics
    stream (the ``[INFER]`` lines); diagnostics go through
    ``roc_tpu.obs.events.emit`` or ``file=sys.stderr``.  Allowed
    surfaces: the console event sink, the report CLI, and this
    package's own CLI — places whose stdout IS their product."""

    name = "stdout-print"
    why = ("stdout is a clean metrics stream; route diagnostics "
           "through roc_tpu.obs.events.emit (or file=sys.stderr for "
           "pre-bus error paths)")
    ALLOW_FILES = {"roc_tpu/obs/events.py", "roc_tpu/report.py",
                   "roc_tpu/analysis/__main__.py",
                   # the prewarm CLI's stdout IS its product (one
                   # machine-readable JSON report line per config)
                   "roc_tpu/prewarm.py",
                   # same for the timeline merger and the regression
                   # sentinel: their stdout is the report/verdict
                   "roc_tpu/obs/timeline.py", "roc_tpu/timeline.py",
                   "roc_tpu/obs/sentinel.py", "roc_tpu/sentinel.py",
                   # the serve export CLI prints one JSON report line
                   # (error paths go to stderr like every CLI here)
                   "roc_tpu/serve/export.py", "roc_tpu/export.py"}

    def select(self, relpath: str) -> bool:
        return relpath not in self.ALLOW_FILES

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _is_name(node.func, "print")):
                continue
            if any(kw.arg == "file" for kw in node.keywords):
                continue    # explicit stream (stderr error paths)
            if (len(node.args) == 1
                    and isinstance(node.args[0], ast.Call)
                    and _is_name(node.args[0].func, "format_metrics")):
                continue    # the sanctioned [INFER] metrics line
            yield Finding(self.name, relpath,
                          "bare print() to stdout", line=node.lineno,
                          key=f"print@{node.lineno}")


class HostSyncHotPathRule(AstRule):
    """Implicit device→host syncs in hot-path modules: a single
    ``jax.device_get`` / ``.item()`` / ``float(arr)`` inside the
    aggregation/kernel/streaming code serializes the dispatch pipeline
    every step — exactly the stall class the async epoch loop exists
    to avoid.  ``float()`` of a plain name or literal (config scalars)
    is not flagged; computed expressions are."""

    name = "host-sync-hot-path"
    why = ("hot-path modules must stay fetch-free: host syncs "
           "serialize the async dispatch pipeline")
    # serve/ is scoped in as a whole: a device_get/.item() inside the
    # request loop serializes every queued microbatch behind one
    # query's fetch — exactly the latency bug class this tier will
    # grow.  The ONE sanctioned fetch (the result itself) carries a
    # pragma at the call site (serve/predictor.py).
    HOT_PREFIXES = ("roc_tpu/ops/", "roc_tpu/kernels/",
                    "roc_tpu/serve/")
    HOT_FILES = {"roc_tpu/core/streaming.py"}

    def select(self, relpath: str) -> bool:
        return (relpath.startswith(self.HOT_PREFIXES)
                or relpath in self.HOT_FILES)

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_attr(node.func, "device_get") or \
                    _is_name(node.func, "device_get"):
                yield Finding(self.name, relpath,
                              "jax.device_get in a hot-path module",
                              line=node.lineno,
                              key=f"device_get@{node.lineno}")
            elif (_is_attr(node.func, "item") and not node.args
                    and not node.keywords):
                yield Finding(self.name, relpath,
                              ".item() in a hot-path module "
                              "(implicit device fetch)",
                              line=node.lineno,
                              key=f"item@{node.lineno}")
            elif (_is_name(node.func, "float") and len(node.args) == 1
                    and not isinstance(node.args[0],
                                       (ast.Constant, ast.Name))):
                yield Finding(self.name, relpath,
                              "float(<expr>) in a hot-path module "
                              "(implicit device fetch on arrays)",
                              line=node.lineno,
                              key=f"float@{node.lineno}")


class SyncH2dInLoopRule(AstRule):
    """Synchronous host→device staging inside a Python loop: a
    ``jax.device_put`` / ``np.ascontiguousarray`` in a ``for``/
    ``while`` body puts the host copy + H2D transfer on the critical
    path of every iteration — exactly the latency-serial pattern the
    staging pool (``core/streaming.py StagingPool``) exists to hide.
    Route block staging through the pool (``_stage_block`` is the one
    sanctioned call site, and it lives outside any loop); genuinely
    cold loops suppress with ``# roc-lint: ok=sync-h2d-in-loop``."""

    name = "sync-h2d-in-loop"
    why = ("a per-iteration device_put/ascontiguousarray serializes "
           "the transfer behind compute; stage through "
           "core/streaming.StagingPool so block k+1's copy runs "
           "under block k's work")
    HOT_PREFIXES = ("roc_tpu/ops/", "roc_tpu/kernels/")
    HOT_FILES = {"roc_tpu/core/streaming.py"}

    def select(self, relpath: str) -> bool:
        return (relpath.startswith(self.HOT_PREFIXES)
                or relpath in self.HOT_FILES)

    LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
                  ast.SetComp, ast.DictComp, ast.GeneratorExp)

    def check(self, tree, relpath):
        seen = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, self.LOOP_NODES):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                if _is_attr(node.func, "device_put") or \
                        _is_name(node.func, "device_put"):
                    what = "device_put"
                elif _is_attr(node.func, "ascontiguousarray") or \
                        _is_name(node.func, "ascontiguousarray"):
                    what = "ascontiguousarray"
                else:
                    continue
                key = f"{what}@{node.lineno}"
                if key in seen:     # nested loops walk twice
                    continue
                seen.add(key)
                yield Finding(self.name, relpath,
                              f"{what} inside a loop body — "
                              "synchronous H2D on the critical path "
                              "(stage through StagingPool)",
                              line=node.lineno, key=key)


class BareJitRule(AstRule):
    """``jax.jit`` in the trainer/parallel layers that bypasses
    ``ObservedJit`` — such steps compile invisibly: no lower/compile
    wall time, no cost/memory introspection, no modeled-vs-actual HBM
    check.  Allowed only lexically inside an ``ObservedJit(...)`` call
    (the ``jitfn=jax.jit(...)`` form for pre-wrapped shard_map
    steps)."""

    name = "bare-jit"
    why = ("steps must compile through ObservedJit so cost/memory "
           "introspection and the modeled-vs-actual HBM check see "
           "them")
    PREFIXES = ("roc_tpu/train/", "roc_tpu/parallel/")

    def select(self, relpath: str) -> bool:
        return relpath.startswith(self.PREFIXES)

    def check(self, tree, relpath):
        observed_spans = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and (_is_name(node.func, "ObservedJit")
                         or _is_attr(node.func, "ObservedJit"))):
                observed_spans.append(
                    (node.lineno, node.end_lineno or node.lineno))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _is_attr(node.func, "jit", base="jax")):
                continue
            if any(lo <= node.lineno <= hi
                   for lo, hi in observed_spans):
                continue    # ObservedJit(jitfn=jax.jit(...)) form
            yield Finding(self.name, relpath,
                          "bare jax.jit bypasses ObservedJit",
                          line=node.lineno,
                          key=f"jit@{node.lineno}")


class PallasInterpretRule(AstRule):
    """Every ``pl.pallas_call`` must plumb ``interpret=`` — kernels
    without it cannot run on the CPU test rig (jax dropped the global
    force_tpu_interpret_mode switch), so their coverage silently
    evaporates."""

    name = "pallas-interpret"
    why = ("kernels must expose interpret= or they are untestable on "
           "the CPU rig")

    def select(self, relpath: str) -> bool:
        return relpath.startswith("roc_tpu/kernels/")

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _is_attr(node.func, "pallas_call")):
                continue
            if any(kw.arg == "interpret" for kw in node.keywords):
                continue
            yield Finding(self.name, relpath,
                          "pallas_call without interpret= plumbing",
                          line=node.lineno,
                          key=f"pallas@{node.lineno}")


class SwallowedExceptionRule(AstRule):
    """Silently swallowed exceptions in the recovery/streaming/
    checkpoint paths: a bare ``except:`` (any body — it eats
    KeyboardInterrupt and SystemExit too), or any handler whose body
    is only ``pass``/``...``.  These are exactly the modules whose job
    is to SURFACE faults — a swallow here converts a diagnosable
    failure (corrupt checkpoint, dead stager, half-written file) into
    silent data loss, the reference's ``exit(1)`` failure model with
    the exit removed.  Genuinely-benign swallows (best-effort cleanup)
    suppress with ``# roc-lint: ok=swallowed-exception`` and a reason,
    like every rule."""

    name = "swallowed-exception"
    why = ("recovery/streaming/checkpoint paths must surface "
           "failures: route them to the resilience event stream or "
           "re-raise, or pragma the line with the why")
    PREFIXES = ("roc_tpu/resilience/",)
    FILES = {"roc_tpu/utils/checkpoint.py",
             "roc_tpu/utils/resilience.py",
             "roc_tpu/core/streaming.py"}

    def select(self, relpath: str) -> bool:
        return (relpath.startswith(self.PREFIXES)
                or relpath in self.FILES)

    @staticmethod
    def _body_is_noop(body) -> bool:
        return all(isinstance(s, ast.Pass)
                   or (isinstance(s, ast.Expr)
                       and isinstance(s.value, ast.Constant)
                       and s.value.value is Ellipsis)
                   for s in body)

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(self.name, relpath,
                              "bare except: swallows KeyboardInterrupt"
                              "/SystemExit too — name the exception",
                              line=node.lineno,
                              key=f"bare-except@{node.lineno}")
            elif self._body_is_noop(node.body):
                yield Finding(self.name, relpath,
                              "exception handler body is only pass — "
                              "the failure vanishes without a trace",
                              line=node.lineno,
                              key=f"except-pass@{node.lineno}")


class EventClockRule(AstRule):
    """Events must go through the bus helper that stamps the clock
    tuple (``obs/events.py emit``): the cross-process timeline merger
    aligns per-process streams on the ``(t, mono, host, proc)`` stamps
    the bus owns, so (a) no call site may hand-pass any of those
    reserved fields to ``emit`` (a caller-supplied ``t=``/``proc=``
    would silently mis-lane the record in the merged trace), and
    (b) no module outside the bus may hand-roll an event record (a
    dict literal carrying both ``"cat"`` and ``"msg"`` keys) — a
    hand-rolled dict written straight to a JSONL file has no clock
    tuple and falls off the merged time axis."""

    name = "event-clock"
    why = ("the bus stamps the (wall, monotonic, host, proc) clock "
           "tuple; hand-stamped or hand-rolled event records break "
           "the cross-process timeline alignment")
    RESERVED = {"t", "mono", "host", "proc"}
    ALLOW_FILES = {"roc_tpu/obs/events.py"}

    def select(self, relpath: str) -> bool:
        return relpath not in self.ALLOW_FILES

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and (
                    _is_name(node.func, "emit")
                    or _is_attr(node.func, "emit")):
                bad = sorted(kw.arg for kw in node.keywords
                             if kw.arg in self.RESERVED)
                if bad:
                    yield Finding(
                        self.name, relpath,
                        f"emit() hand-passes reserved clock field(s) "
                        f"{bad} — the bus stamps the clock tuple",
                        line=node.lineno,
                        key=f"emit-clock@{node.lineno}")
            elif isinstance(node, ast.Dict):
                keys = {k.value for k in node.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
                if {"cat", "msg"} <= keys:
                    yield Finding(
                        self.name, relpath,
                        "hand-rolled event record (dict literal with "
                        "'cat' and 'msg' keys) — construct events "
                        "through obs.events.emit so the clock tuple "
                        "is stamped",
                        line=node.lineno,
                        key=f"event-dict@{node.lineno}")


class MetricAdhocRule(AstRule):
    """Serving/training hot paths must record metrics through the
    streaming registry (``obs/metrics_registry.py``), not ad-hoc
    instance state: a hand-rolled ``self._n_foo += 1`` counter has no
    window and no snapshot, and an unbounded ``*_ms``/``*_lat`` list
    grows without limit AND costs an O(n) sort at every quantile read
    — exactly the failure modes the registry's O(1) counters and
    log-bucket histograms exist to close.  Flags (a) ``+=``/``-=``
    augmented assignment onto a ``_n_*`` attribute and (b)
    ``.append(...)`` onto an attribute ending ``_ms``/``_lat``.
    Sanctioned buffers (the trainer's timeline span laps) carry a
    ``# roc-lint: ok=metric-adhoc`` pragma saying why."""

    name = "metric-adhoc"
    why = ("hot-path counters/latency samples belong in the metrics "
           "registry (windowed, O(1), snapshot-able) — ad-hoc "
           "attributes have no window and unbounded lists leak")
    PREFIXES = ("roc_tpu/serve/",)
    FILES = {"roc_tpu/train/trainer.py"}

    def select(self, relpath: str) -> bool:
        return (relpath.startswith(self.PREFIXES)
                or relpath in self.FILES)

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.target, ast.Attribute)
                    and node.target.attr.startswith("_n_")):
                yield Finding(
                    self.name, relpath,
                    f"ad-hoc counter '{node.target.attr} "
                    f"{type(node.op).__name__}=' — use a registry "
                    f"Counter (windowed, O(1) inc)",
                    line=node.lineno,
                    key=f"adhoc-counter@{node.lineno}")
            elif (isinstance(node, ast.Call)
                  and _is_attr(node.func, "append")
                  and isinstance(node.func.value, ast.Attribute)
                  and node.func.value.attr.endswith(("_ms", "_lat"))):
                yield Finding(
                    self.name, relpath,
                    f"ad-hoc latency list "
                    f"'{node.func.value.attr}.append' — use a "
                    f"registry Histogram (log-bucket, bounded, "
                    f"windowed quantiles)",
                    line=node.lineno,
                    key=f"adhoc-latency@{node.lineno}")


class DequantHotPathRule(AstRule):
    """Materializing a full fp32 copy of a quantized serving table
    inside ``roc_tpu/serve/``: the whole point of int8/fp8 tables
    (``serve/quant.py``) is that the ``[V, F]`` buffer never widens —
    the serve programs gather the bucket's rows and dequantize
    IN-REGISTER.  An ``.astype(float32)`` (or
    ``asarray(..., dtype=float32)`` / ``float32(...)`` cast) applied
    to a table/stage-named array undoes the capacity win in one line
    and doubles+ the replica's memory right where it is scarcest.
    Sanctioned sites — host-side build/load paths and rows-only
    refresh slices — carry a ``# roc-lint: ok=dequant-hot-path``
    pragma saying why they are not the hot path."""

    name = "dequant-hot-path"
    why = ("serve/ must dequantize gathered rows in-register — a "
           "full fp32 copy of a [V, F] table forfeits the quantized "
           "capacity win; pragma host-side build/refresh sites")

    def select(self, relpath: str) -> bool:
        return relpath.startswith("roc_tpu/serve/")

    @staticmethod
    def _is_f32(node: ast.AST) -> bool:
        return (_is_attr(node, "float32") or _is_name(node, "float32")
                or (isinstance(node, ast.Constant)
                    and node.value == "float32"))

    @staticmethod
    def _tableish(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            ident = (n.id if isinstance(n, ast.Name)
                     else n.attr if isinstance(n, ast.Attribute)
                     else None)
            if ident and ("table" in ident.lower()
                          or "stage" in ident.lower()):
                return True
        return False

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "astype"
                    and node.args and self._is_f32(node.args[0])
                    and self._tableish(f.value)):
                yield Finding(
                    self.name, relpath,
                    "full fp32 .astype on a table-shaped array — "
                    "dequantize gathered rows in-register instead",
                    line=node.lineno, key=f"astype@{node.lineno}")
            elif (isinstance(f, ast.Attribute) and f.attr == "asarray"
                    and node.args and self._tableish(node.args[0])
                    and any(kw.arg == "dtype" and self._is_f32(kw.value)
                            for kw in node.keywords)):
                yield Finding(
                    self.name, relpath,
                    "asarray(<table>, dtype=float32) materializes a "
                    "full fp32 table copy",
                    line=node.lineno, key=f"asarray@{node.lineno}")
            elif (self._is_f32(f) and node.args
                    and self._tableish(node.args[0])):
                yield Finding(
                    self.name, relpath,
                    "float32(<table>) cast materializes a full fp32 "
                    "table copy",
                    line=node.lineno, key=f"cast@{node.lineno}")


RULES: List[AstRule] = [StdoutPrintRule(), HostSyncHotPathRule(),
                        SyncH2dInLoopRule(), BareJitRule(),
                        PallasInterpretRule(),
                        SwallowedExceptionRule(), EventClockRule(),
                        MetricAdhocRule(), DequantHotPathRule()]


def run_ast_lint(root: str,
                 select: Optional[List[str]] = None) -> List[Finding]:
    """Run the AST rules over ``<root>/roc_tpu/**/*.py``.  ``select``
    restricts to the named rules (unknown names raise — a typo must
    not silently skip a gate)."""
    rules = RULES
    if select is not None:
        from .concurrency_lint import CONCURRENCY_RULES
        from .driver import is_trace_rule   # lazy: no import cycle
        from .protocol_lint import PROTOCOL_RULES
        known = {r.name for r in RULES}
        bad = [s for s in select
               if s not in known and not is_trace_rule(s)
               and s not in CONCURRENCY_RULES
               and s not in PROTOCOL_RULES]
        if bad:
            raise ValueError(f"unknown lint rule(s): {bad}; "
                             f"AST rules: {sorted(known)}")
        rules = [r for r in RULES if r.name in select]
    findings: List[Finding] = []
    base = pathlib.Path(root)
    for path in sorted(base.glob("roc_tpu/**/*.py")):
        rel = path.relative_to(base).as_posix()
        applicable = [r for r in rules if r.select(rel)]
        if not applicable:
            continue
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for rule in applicable:
            for f in rule.check(tree, rel):
                if not pragma_ok(lines, f.line, rule.name):
                    findings.append(f)
    return findings
