"""``python -m roc_tpu.analysis`` — the roc-lint CLI.

Exit code 0 means the tree is clean modulo the baseline; any
unbaselined finding exits 1 (lint semantics — this IS the gate the
tier runs).  Stdout is the product: one ``unit:line: [rule] message``
line per finding, then a summary.

Usage:
    python -m roc_tpu.analysis [--strict]          # full run
    python -m roc_tpu.analysis --select stdout-print   # one rule
    python -m roc_tpu.analysis --select concurrency    # level six
    python -m roc_tpu.analysis --select sharding       # level seven
    python -m roc_tpu.analysis --select protocol       # level eight
    python -m roc_tpu.analysis --update-baseline   # shrink ratchet
    python -m roc_tpu.analysis --json              # machine-readable

``--json`` prints one JSON object on stdout — findings, baseline
split, and the program-space compile-budget reports with full
program-key sets — so CI and the bench probe can diff program counts
across commits without parsing text.

The baseline (``scripts/lint_baseline.json``) is ratchet-only:
``--update-baseline`` rewrites it as the INTERSECTION of its current
entries and the findings that still fire — it can only shrink.  New
findings are fixed at the source or suppressed with an explanatory
``# roc-lint: ok=<rule>`` pragma, never absorbed.  ``--strict``
additionally fails on stale baseline entries, forcing the shrink to
be committed.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _default_root() -> str:
    """Prefer CWD when it holds a roc_tpu/ tree (the thin-wrapper
    scripts cd to the repo they lint), else the checkout this module
    was imported from."""
    if os.path.isdir(os.path.join(os.getcwd(), "roc_tpu")):
        return os.getcwd()
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m roc_tpu.analysis",
        description="roc-lint: jaxpr/HLO/AST static analysis, "
                    "ratcheted via scripts/lint_baseline.json")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: cwd when it has "
                        "a roc_tpu/ tree, else this checkout)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names (default: all); "
                        "an AST-only selection skips the jax trace "
                        "stage entirely.  'concurrency' expands to "
                        "every level-six concurrency/signal-safety "
                        "rule (jax-free — the scripts/test.sh and "
                        "round6_chain.sh preflight selection); "
                        "'sharding' expands to every level-seven "
                        "sharding/replication rule (runs the rig "
                        "builds + jaxpr walks, no compiles); "
                        "'protocol' expands to every level-eight "
                        "protocol-audit/model-check rule (jax-free "
                        "— preflight class)")
    p.add_argument("--no-trace", action="store_true",
                   help="skip the jaxpr/HLO trace stage (AST only)")
    p.add_argument("--baseline", default=None,
                   help="baseline path (default: "
                        "<root>/scripts/lint_baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="shrink-only rewrite of the baseline "
                        "(drops entries that no longer fire)")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries "
                        "(ratchet shrink must be committed)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule names and exit")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output: one JSON object "
                        "(findings + program-key sets) on stdout")
    args = p.parse_args(argv)

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        # group aliases: 'concurrency' names the whole level-six rule
        # set (expanded BEFORE the trace gating below so a
        # concurrency-only preflight never touches or forces jax);
        # 'sharding' names the level-seven set the same way
        from .concurrency_lint import CONCURRENCY_RULES
        from .protocol_lint import PROTOCOL_RULES
        from .sharding_lint import SHARDING_RULES
        groups = {"concurrency": CONCURRENCY_RULES,
                  "sharding": SHARDING_RULES,
                  "protocol": PROTOCOL_RULES}
        select = [r for s in select
                  for r in groups.get(s, (s,))]
    trace = not args.no_trace
    from .driver import is_trace_rule
    if trace and (select is None
                  or any(is_trace_rule(s) for s in select)):
        # the trace stage runs on the 8-virtual-device CPU rig,
        # unconditionally: the baseline fingerprints are CPU-rig
        # artifacts, and a TPU-host invocation must not spend chip
        # time (or drift the HLO) on a lint pass
        from . import force_cpu_rig
        force_cpu_rig()

    from .driver import all_rule_names, analyze
    from .findings import (load_baseline, shrink_baseline,
                           split_findings)

    if args.list_rules:
        for name in all_rule_names():
            print(name)
        return 0
    if select:
        known = set(all_rule_names())
        bad = sorted(set(select) - known)
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}; see "
                  f"--list-rules")
            return 2

    root = args.root or _default_root()
    baseline_path = args.baseline or os.path.join(
        root, "scripts", "lint_baseline.json")
    extras: dict = {}
    from .findings import load_budget, load_program_budget
    findings = analyze(root, select=select, trace=trace,
                       program_budget=load_program_budget(
                           baseline_path),
                       replication_budget=load_budget(
                           baseline_path, "replication_budget"),
                       extras=extras)
    reports = extras.get("programspace", [])
    sh_reports = extras.get("sharding", [])
    # stale-entry accounting and the shrink ratchet are scoped to the
    # rules that actually ran: an AST-only / --select run must not
    # declare trace-rule baseline entries "no longer firing"
    active = set(select) if select else set(all_rule_names())
    if not trace:
        active = {r for r in active if not is_trace_rule(r)}
    # the two numeric ratchet TRACKS (findings.BUDGET_SECTIONS) share
    # one set of semantics — bound over measurement = finding (the
    # auditor emits it), measurement below bound = slack, missing
    # bound = tripwire disarmed, bound for a config that no longer
    # exists = orphan; slack/orphans/unbounded all fail --strict
    # until --update-baseline commits the shrink — so they are
    # processed by ONE loop over track descriptors
    from .driver import _needs_programspace, _needs_sharding
    ps_ran = trace and _needs_programspace(select)
    sh_ran = trace and _needs_sharding(select)
    tracks = [
        {"section": "program_budget", "label": "program budget",
         "ran": ps_ran, "reports": reports,
         "measured_key": "programs", "noun": "count",
         "guards": "the compile-explosion bound no longer guards "
                   "anything; "},
        {"section": "replication_budget",
         "label": "replication budget",
         "ran": sh_ran, "reports": sh_reports,
         "measured_key": "replicated_bytes", "noun": "bytes",
         "guards": ""},
    ]
    rig_names: set = set()
    if any(t["ran"] for t in tracks):
        from .programspace import rig_configs
        rig_names = set(rig_configs())

    def _orphans(track) -> List[str]:
        # bounds for rig configs that no longer EXIST (renamed or
        # removed — not merely unhosted on this box, whose bound is
        # deliberately kept) would otherwise disarm the tripwire
        # silently: the renamed config restarts at budget=None
        if not track["ran"]:
            return []
        return sorted(set(load_budget(baseline_path,
                                      track["section"]))
                      - rig_names)

    for t in tracks:
        t["orphans"] = _orphans(t)
    baseline = load_baseline(baseline_path)
    new, old, stale = split_findings(findings, baseline,
                                     active_rules=active)
    dropped = 0
    if args.update_baseline:
        # shrink FIRST (findings AND budgets), then re-split against
        # the updated file: all output below must describe the state
        # this run LEAVES, not the entries it just removed — a CI
        # consumer would otherwise re-flag a ratchet the same
        # invocation already cleared, and a first-ever run would
        # print bounds instructing the user to run the flag they are
        # running
        from .findings import shrink_budget
        kept = shrink_baseline(baseline_path, findings,
                               active_rules=active)
        dropped = len(baseline) - len(kept)
        for t in tracks:
            if not t["ran"]:
                continue
            budget = shrink_budget(
                baseline_path, t["section"],
                {r["config"]: r[t["measured_key"]]
                 for r in t["reports"]},
                known=rig_names)
            for rep in t["reports"]:
                b = budget.get(rep["config"])
                rep["budget"] = b
                if b is not None:
                    rep["delta"] = rep[t["measured_key"]] - b
            t["orphans"] = _orphans(t)
        baseline = load_baseline(baseline_path)
        new, old, stale = split_findings(findings, baseline,
                                         active_rules=active)
    # budget slack — same ratchet semantics as stale findings: a
    # measurement BELOW the recorded bound must be committed via
    # --update-baseline, or a later regression would hide inside the
    # slack and the tripwire would never fire.  A measured config
    # with NO bound at all is the limiting case of slack (infinite
    # headroom — the tripwire is disarmed for it), so under --strict
    # it fails the same way until --update-baseline initializes.
    for t in tracks:
        t["slack"] = [r for r in t["reports"]
                      if r.get("delta") is not None
                      and r["delta"] < 0]
        t["unbounded"] = [r for r in t["reports"]
                          if r.get("budget") is None]
    any_ratchet_debt = bool(stale) or any(
        t["slack"] or t["orphans"] or t["unbounded"] for t in tracks)
    prog, repl = tracks

    if args.json:
        import json as _json
        payload = {
            "findings": [
                {"rule": f.rule, "unit": f.unit, "line": f.line,
                 "msg": f.msg, "fingerprint": f.fingerprint,
                 "baselined": f.fingerprint in baseline,
                 "detail": f.detail}
                for f in new + old],
            "stale": sorted(stale),
            "budget_stale": prog["orphans"],
            "program_space": reports,
            "sharding": sh_reports,
            "replication_budget_stale": repl["orphans"],
            "concurrency_surface": extras.get("concurrency"),
            "protocol_surface": extras.get("protocol"),
            "summary": {"new": len(new), "baselined": len(old),
                        "stale": len(stale),
                        "budget_slack": len(prog["slack"]),
                        "budget_stale": len(prog["orphans"]),
                        "budget_unbounded": len(prog["unbounded"]),
                        "replication_slack": len(repl["slack"]),
                        "replication_stale": len(repl["orphans"]),
                        "replication_unbounded":
                            len(repl["unbounded"])},
        }
        print(_json.dumps(payload, indent=2))
        return (1 if new or (any_ratchet_debt and args.strict)
                else 0)

    for f in new:
        print(f.render())
    for f in old:
        print(f"{f.render()}  [baselined]")
    # the program-space compile budget — the static compile-wall
    # tripwire.  scripts/test.sh's pre-flight surfaces these lines, so
    # a PR that adds a compiled-program shape shows its delta before
    # the test tier even starts (red when it grew and a tty is
    # watching).
    for rep in reports:
        b = rep.get("budget")
        delta = rep.get("delta")
        d_txt = ("no baseline — run --update-baseline" if b is None
                 else f"baseline {b}, delta {delta:+d}")
        line = (f"program budget {rep['config']}: "
                f"{rep['programs']} programs, modeled compile "
                f"{rep['modeled_compile_ms'] / 1e3:.1f}s ({d_txt})")
        if delta is not None and delta > 0 and sys.stdout.isatty():
            line = f"\x1b[31m{line}\x1b[0m"
        print(line)
    # the sharding auditor's replication budget — the 2-D-mesh
    # tripwire: replicated bytes/step on the canonical candidate
    # mesh, ratcheted exactly like the program counts above
    for rep in sh_reports:
        b = rep.get("budget")
        delta = rep.get("delta")
        d_txt = ("no baseline — run --update-baseline" if b is None
                 else f"baseline {b}, delta {delta:+d}")
        line = (f"replication budget {rep['config']}: "
                f"{rep['replicated_bytes']} replicated B/step on "
                f"{rep['canonical_shape'][0]}x"
                f"{rep['canonical_shape'][1]}, "
                f"{rep['full_width_sites']} full-width site(s) "
                f"({d_txt})")
        if delta is not None and delta > 0 and sys.stdout.isatty():
            line = f"\x1b[31m{line}\x1b[0m"
        print(line)
    if args.update_baseline:
        print(f"baseline: kept {len(baseline)}, dropped {dropped} "
              f"stale entr{'y' if dropped == 1 else 'ies'} "
              f"({baseline_path})")
    else:
        if stale:
            verb = "FAIL" if args.strict else "note"
            print(f"{verb}: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} no longer "
                  f"fire(s) — run --update-baseline to ratchet down:")
            for fp in sorted(stale):
                print(f"  {fp}")
        for t in tracks:
            verb = "FAIL" if args.strict else "note"
            if t["slack"]:
                print(f"{verb}: {len(t['slack'])} {t['label']}(s) "
                      f"above the measured {t['noun']} — run "
                      f"--update-baseline to ratchet down:")
                for rep in t["slack"]:
                    print(f"  {rep['config']}: "
                          f"{rep[t['measured_key']]} measured < "
                          f"{rep['budget']} baselined")
            if t["orphans"]:
                print(f"{verb}: {len(t['orphans'])} {t['label']} "
                      f"entr{'y' if len(t['orphans']) == 1 else 'ies'}"
                      f" for unknown rig config(s) — {t['guards']}run "
                      f"--update-baseline to drop:")
                for cfg in t["orphans"]:
                    print(f"  {cfg}")
            if t["unbounded"] and args.strict:
                print(f"FAIL: {len(t['unbounded'])} measured "
                      f"config(s) have no {t['section']} bound "
                      f"(tripwire disarmed) — run --update-baseline "
                      f"to initialize:")
                for rep in t["unbounded"]:
                    print(f"  {rep['config']}: "
                          f"{rep[t['measured_key']]} measured")

    print(f"roc-lint: {len(new)} new, {len(old)} baselined, "
          f"{len(stale)} stale")
    if new:
        return 1
    if any_ratchet_debt and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
