"""``python -m roc_tpu.analysis`` — the roc-lint CLI.

Exit code 0 means the tree is clean modulo the baseline; any
unbaselined finding exits 1 (lint semantics — this IS the gate the
tier runs).  Stdout is the product: one ``unit:line: [rule] message``
line per finding, then a summary.

Usage:
    python -m roc_tpu.analysis [--strict]          # full run
    python -m roc_tpu.analysis --select stdout-print   # one rule
    python -m roc_tpu.analysis --select concurrency    # level six
    python -m roc_tpu.analysis --update-baseline   # shrink ratchet
    python -m roc_tpu.analysis --json              # machine-readable

``--json`` prints one JSON object on stdout — findings, baseline
split, and the program-space compile-budget reports with full
program-key sets — so CI and the bench probe can diff program counts
across commits without parsing text.

The baseline (``scripts/lint_baseline.json``) is ratchet-only:
``--update-baseline`` rewrites it as the INTERSECTION of its current
entries and the findings that still fire — it can only shrink.  New
findings are fixed at the source or suppressed with an explanatory
``# roc-lint: ok=<rule>`` pragma, never absorbed.  ``--strict``
additionally fails on stale baseline entries, forcing the shrink to
be committed.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _default_root() -> str:
    """Prefer CWD when it holds a roc_tpu/ tree (the thin-wrapper
    scripts cd to the repo they lint), else the checkout this module
    was imported from."""
    if os.path.isdir(os.path.join(os.getcwd(), "roc_tpu")):
        return os.getcwd()
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m roc_tpu.analysis",
        description="roc-lint: jaxpr/HLO/AST static analysis, "
                    "ratcheted via scripts/lint_baseline.json")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: cwd when it has "
                        "a roc_tpu/ tree, else this checkout)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names (default: all); "
                        "an AST-only selection skips the jax trace "
                        "stage entirely.  'concurrency' expands to "
                        "every level-six concurrency/signal-safety "
                        "rule (jax-free — the scripts/test.sh and "
                        "round6_chain.sh preflight selection)")
    p.add_argument("--no-trace", action="store_true",
                   help="skip the jaxpr/HLO trace stage (AST only)")
    p.add_argument("--baseline", default=None,
                   help="baseline path (default: "
                        "<root>/scripts/lint_baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="shrink-only rewrite of the baseline "
                        "(drops entries that no longer fire)")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries "
                        "(ratchet shrink must be committed)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule names and exit")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output: one JSON object "
                        "(findings + program-key sets) on stdout")
    args = p.parse_args(argv)

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        # group alias: 'concurrency' names the whole level-six rule
        # set, expanded BEFORE the trace gating below so a
        # concurrency-only preflight never touches (or forces) jax
        from .concurrency_lint import CONCURRENCY_RULES
        select = [r for s in select for r in
                  (CONCURRENCY_RULES if s == "concurrency" else (s,))]
    trace = not args.no_trace
    from .driver import is_trace_rule
    if trace and (select is None
                  or any(is_trace_rule(s) for s in select)):
        # the trace stage runs on the 8-virtual-device CPU rig,
        # unconditionally: the baseline fingerprints are CPU-rig
        # artifacts, and a TPU-host invocation must not spend chip
        # time (or drift the HLO) on a lint pass
        from . import force_cpu_rig
        force_cpu_rig()

    from .driver import all_rule_names, analyze
    from .findings import (load_baseline, shrink_baseline,
                           shrink_program_budget, split_findings)

    if args.list_rules:
        for name in all_rule_names():
            print(name)
        return 0
    if select:
        known = set(all_rule_names())
        bad = sorted(set(select) - known)
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}; see "
                  f"--list-rules")
            return 2

    root = args.root or _default_root()
    baseline_path = args.baseline or os.path.join(
        root, "scripts", "lint_baseline.json")
    extras: dict = {}
    from .findings import load_program_budget
    findings = analyze(root, select=select, trace=trace,
                       program_budget=load_program_budget(
                           baseline_path),
                       extras=extras)
    reports = extras.get("programspace", [])
    # stale-entry accounting and the shrink ratchet are scoped to the
    # rules that actually ran: an AST-only / --select run must not
    # declare trace-rule baseline entries "no longer firing"
    active = set(select) if select else set(all_rule_names())
    if not trace:
        active = {r for r in active if not is_trace_rule(r)}
    # program_budget keys get the same stale accounting as finding
    # fingerprints, scoped to runs where the auditor level ran: a
    # bound for a config name that no longer EXISTS in the rig set
    # (renamed/removed — not merely unhosted on this box, whose bound
    # is deliberately kept) is an orphan that would otherwise disarm
    # the compile-explosion tripwire silently (the renamed config
    # restarts at budget=None, which never fires)
    from .driver import _needs_programspace
    ps_ran = trace and _needs_programspace(select)
    rig_names: set = set()
    if ps_ran:
        from .programspace import rig_configs
        rig_names = set(rig_configs())

    def _budget_orphans() -> List[str]:
        if not ps_ran:
            return []
        return sorted(set(load_program_budget(baseline_path))
                      - rig_names)

    orphans = _budget_orphans()
    baseline = load_baseline(baseline_path)
    new, old, stale = split_findings(findings, baseline,
                                     active_rules=active)
    dropped = 0
    if args.update_baseline:
        # shrink FIRST (findings AND budget), then re-split against
        # the updated file: all output below must describe the state
        # this run LEAVES, not the entries it just removed — a CI
        # consumer would otherwise re-flag a ratchet the same
        # invocation already cleared, and a first-ever run would
        # print bounds instructing the user to run the flag they are
        # running
        kept = shrink_baseline(baseline_path, findings,
                               active_rules=active)
        dropped = len(baseline) - len(kept)
        if ps_ran:
            budget = shrink_program_budget(
                baseline_path,
                {r["config"]: r["programs"] for r in reports},
                known=rig_names)
            for rep in reports:
                b = budget.get(rep["config"])
                rep["budget"] = b
                if b is not None:
                    rep["delta"] = rep["programs"] - b
        baseline = load_baseline(baseline_path)
        new, old, stale = split_findings(findings, baseline,
                                         active_rules=active)
        orphans = _budget_orphans()
    # budget slack — same ratchet semantics as stale findings: a
    # measured program count BELOW the recorded bound must be
    # committed via --update-baseline, or a later program-count
    # regression would hide inside the slack and the compile-wall
    # tripwire would never fire.  A measured config with NO bound at
    # all is the limiting case of slack (infinite headroom — the
    # tripwire is disarmed for it), so under --strict it fails the
    # same way until --update-baseline initializes the bound.
    slack = [r for r in reports if r.get("delta") is not None
             and r["delta"] < 0]
    unbounded = [r for r in reports if r.get("budget") is None]

    if args.json:
        import json as _json
        payload = {
            "findings": [
                {"rule": f.rule, "unit": f.unit, "line": f.line,
                 "msg": f.msg, "fingerprint": f.fingerprint,
                 "baselined": f.fingerprint in baseline,
                 "detail": f.detail}
                for f in new + old],
            "stale": sorted(stale),
            "budget_stale": orphans,
            "program_space": reports,
            "concurrency_surface": extras.get("concurrency"),
            "summary": {"new": len(new), "baselined": len(old),
                        "stale": len(stale),
                        "budget_slack": len(slack),
                        "budget_stale": len(orphans),
                        "budget_unbounded": len(unbounded)},
        }
        print(_json.dumps(payload, indent=2))
        return (1 if new or ((stale or slack or orphans or unbounded)
                             and args.strict)
                else 0)

    for f in new:
        print(f.render())
    for f in old:
        print(f"{f.render()}  [baselined]")
    # the program-space compile budget — the static compile-wall
    # tripwire.  scripts/test.sh's pre-flight surfaces these lines, so
    # a PR that adds a compiled-program shape shows its delta before
    # the test tier even starts (red when it grew and a tty is
    # watching).
    for rep in reports:
        b = rep.get("budget")
        delta = rep.get("delta")
        d_txt = ("no baseline — run --update-baseline" if b is None
                 else f"baseline {b}, delta {delta:+d}")
        line = (f"program budget {rep['config']}: "
                f"{rep['programs']} programs, modeled compile "
                f"{rep['modeled_compile_ms'] / 1e3:.1f}s ({d_txt})")
        if delta is not None and delta > 0 and sys.stdout.isatty():
            line = f"\x1b[31m{line}\x1b[0m"
        print(line)
    if args.update_baseline:
        print(f"baseline: kept {len(baseline)}, dropped {dropped} "
              f"stale entr{'y' if dropped == 1 else 'ies'} "
              f"({baseline_path})")
    else:
        if stale:
            verb = "FAIL" if args.strict else "note"
            print(f"{verb}: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} no longer "
                  f"fire(s) — run --update-baseline to ratchet down:")
            for fp in sorted(stale):
                print(f"  {fp}")
        if slack:
            verb = "FAIL" if args.strict else "note"
            print(f"{verb}: {len(slack)} program budget(s) above the "
                  f"measured count — run --update-baseline to "
                  f"ratchet down:")
            for rep in slack:
                print(f"  {rep['config']}: {rep['programs']} measured"
                      f" < {rep['budget']} baselined")
        if orphans:
            verb = "FAIL" if args.strict else "note"
            print(f"{verb}: {len(orphans)} program budget entr"
                  f"{'y' if len(orphans) == 1 else 'ies'} for "
                  f"unknown rig config(s) — the compile-explosion "
                  f"bound no longer guards anything; run "
                  f"--update-baseline to drop:")
            for cfg in orphans:
                print(f"  {cfg}")
        if unbounded and args.strict:
            print(f"FAIL: {len(unbounded)} measured config(s) have "
                  f"no program_budget bound (tripwire disarmed) — "
                  f"run --update-baseline to initialize:")
            for rep in unbounded:
                print(f"  {rep['config']}: {rep['programs']} measured")

    print(f"roc-lint: {len(new)} new, {len(old)} baselined, "
          f"{len(stale)} stale")
    if new:
        return 1
    if (stale or slack or orphans or unbounded) and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
