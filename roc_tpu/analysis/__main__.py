"""``python -m roc_tpu.analysis`` — the roc-lint CLI.

Exit code 0 means the tree is clean modulo the baseline; any
unbaselined finding exits 1 (lint semantics — this IS the gate the
tier runs).  Stdout is the product: one ``unit:line: [rule] message``
line per finding, then a summary.

Usage:
    python -m roc_tpu.analysis [--strict]          # full run
    python -m roc_tpu.analysis --select stdout-print   # one rule
    python -m roc_tpu.analysis --update-baseline   # shrink ratchet

The baseline (``scripts/lint_baseline.json``) is ratchet-only:
``--update-baseline`` rewrites it as the INTERSECTION of its current
entries and the findings that still fire — it can only shrink.  New
findings are fixed at the source or suppressed with an explanatory
``# roc-lint: ok=<rule>`` pragma, never absorbed.  ``--strict``
additionally fails on stale baseline entries, forcing the shrink to
be committed.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _default_root() -> str:
    """Prefer CWD when it holds a roc_tpu/ tree (the thin-wrapper
    scripts cd to the repo they lint), else the checkout this module
    was imported from."""
    if os.path.isdir(os.path.join(os.getcwd(), "roc_tpu")):
        return os.getcwd()
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m roc_tpu.analysis",
        description="roc-lint: jaxpr/HLO/AST static analysis, "
                    "ratcheted via scripts/lint_baseline.json")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: cwd when it has "
                        "a roc_tpu/ tree, else this checkout)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names (default: all); "
                        "an AST-only selection skips the jax trace "
                        "stage entirely")
    p.add_argument("--no-trace", action="store_true",
                   help="skip the jaxpr/HLO trace stage (AST only)")
    p.add_argument("--baseline", default=None,
                   help="baseline path (default: "
                        "<root>/scripts/lint_baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="shrink-only rewrite of the baseline "
                        "(drops entries that no longer fire)")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries "
                        "(ratchet shrink must be committed)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule names and exit")
    args = p.parse_args(argv)

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    trace = not args.no_trace
    from .driver import is_trace_rule
    if trace and (select is None
                  or any(is_trace_rule(s) for s in select)):
        # the trace stage runs on the 8-virtual-device CPU rig,
        # unconditionally: the baseline fingerprints are CPU-rig
        # artifacts, and a TPU-host invocation must not spend chip
        # time (or drift the HLO) on a lint pass.  jax is ALREADY
        # imported by the time -m reaches here (roc_tpu/__init__
        # pulls it in), so the env var alone is latched-and-ignored —
        # force the platform through jax.config like tests/conftest.py
        # does; XLA_FLAGS is still read at CPU-client init, so the
        # virtual-device count append works.
        os.environ["JAX_PLATFORMS"] = "cpu"   # children / consistency
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")

    from .driver import all_rule_names, analyze
    from .findings import load_baseline, shrink_baseline, split_findings

    if args.list_rules:
        for name in all_rule_names():
            print(name)
        return 0
    if select:
        known = set(all_rule_names())
        bad = sorted(set(select) - known)
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}; see "
                  f"--list-rules")
            return 2

    root = args.root or _default_root()
    baseline_path = args.baseline or os.path.join(
        root, "scripts", "lint_baseline.json")
    findings = analyze(root, select=select, trace=trace)
    # stale-entry accounting and the shrink ratchet are scoped to the
    # rules that actually ran: an AST-only / --select run must not
    # declare trace-rule baseline entries "no longer firing"
    active = set(select) if select else set(all_rule_names())
    if not trace:
        active = {r for r in active if not is_trace_rule(r)}
    baseline = load_baseline(baseline_path)
    new, old, stale = split_findings(findings, baseline,
                                     active_rules=active)

    for f in new:
        print(f.render())
    for f in old:
        print(f"{f.render()}  [baselined]")
    if args.update_baseline:
        kept = shrink_baseline(baseline_path, findings,
                               active_rules=active)
        dropped = len(baseline) - len(kept)
        print(f"baseline: kept {len(kept)}, dropped {dropped} stale "
              f"entr{'y' if dropped == 1 else 'ies'} "
              f"({baseline_path})")
        stale = set()
    elif stale:
        verb = "FAIL" if args.strict else "note"
        print(f"{verb}: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer "
              f"fire(s) — run --update-baseline to ratchet down:")
        for fp in sorted(stale):
            print(f"  {fp}")

    print(f"roc-lint: {len(new)} new, {len(old)} baselined, "
          f"{len(stale)} stale")
    if new:
        return 1
    if stale and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
