"""Rules over the optimized HLO text + ``cost_analysis`` capture.

The compile observer (obs/compile_watch.py) already lowers and
compiles the step AOT; this layer inspects what XLA actually built:

- [hlo-large-copy] ``copy`` / ``transpose`` instructions materializing
  activation-scale ([V, F]) tensors OUTSIDE fusions — each one is a
  full HBM round trip the fusion pipeline failed to elide (layout
  mismatches at custom-call/donation boundaries are the usual cause).
- [hlo-bytes-model] executable-level ``bytes accessed`` exceeding the
  core/memory.py plan estimate by a configurable factor — the static
  analog of ObservedJit's modeled-vs-actual warning, catching
  catastrophic traffic blowups (an accidental [V, V] materialization,
  a gather that stopped fusing) before a chip run pays for them.
"""

from __future__ import annotations

import re
from typing import List, Optional

from .findings import Finding

# `  %x.1 = f32[192,48]{1,0} copy(...)` / `transpose(`; shape groups:
# dtype, comma-dims
_COPY_RE = re.compile(
    r"=\s*([a-z][a-z0-9]*)\[([0-9,]*)\][^ ]*\s+(copy|transpose)\(")
# computation headers: `%fused_computation.3 (param_0: ...) -> ... {`
# and `ENTRY %main ... {`
_COMP_RE = re.compile(r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)\s*->")


def _shape_elems(dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def check_large_copy(unit: str, hlo_text: str, copy_min_elems: int
                     ) -> List[Finding]:
    """Flag un-fused copy/transpose of tensors >= ``copy_min_elems``
    elements.  Instructions inside ``fused_computation`` bodies are
    skipped — there the transpose is folded into the fusion's
    reads/writes, not a separate materialization."""
    out: List[Finding] = []
    in_fusion = False
    for line in hlo_text.splitlines():
        header = _COMP_RE.match(line)
        if header and line.rstrip().endswith("{"):
            in_fusion = "fused" in header.group(2)
            continue
        if in_fusion:
            continue
        m = _COPY_RE.search(line)
        if not m:
            continue
        dtype, dims, op = m.groups()
        n = _shape_elems(dims)
        if n >= copy_min_elems:
            out.append(Finding(
                "hlo-large-copy", unit,
                f"un-fused {op} materializes {dtype}[{dims}] "
                f"({n} elems >= activation scale {copy_min_elems}) — "
                f"a full HBM round trip the fusion pipeline missed",
                key=f"{op}|{dtype}[{dims}]"))
    return out


def check_bytes_model(unit: str, bytes_accessed: Optional[float],
                      modeled_bytes: Optional[int],
                      factor: float = 32.0) -> List[Finding]:
    """Flag executables whose measured traffic exceeds ``factor`` x
    the memory model's step estimate.  The factor is deliberately
    loose: bytes-accessed counts every pass over every buffer, so
    legitimate multi-pass aggregation runs a small multiple of
    residency — only order-of-magnitude blowups indicate a
    materialization bug."""
    if not bytes_accessed or not modeled_bytes:
        return []    # introspection unavailable: nothing to hold
    if bytes_accessed <= factor * modeled_bytes:
        return []
    return [Finding(
        "hlo-bytes-model", unit,
        f"bytes accessed {bytes_accessed:.3g} exceeds {factor:g}x the "
        f"core/memory.py estimate ({modeled_bytes} B) — the step is "
        f"moving far more data than the plan modeled",
        key="bytes-model")]
