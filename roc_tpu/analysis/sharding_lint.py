"""Sharding & replication auditor — roc-lint level seven.

The ROADMAP's top open item is the ``(parts, model)`` 2-D mesh: today
every layer materializes full-width ``[V_p, F]`` activations and
replicates all parameters, so the F axis is dead parallelism.  In the
GSPMD/pjit lineage that refactor will follow, shardings are
*propagated* — which means a single unconstrained op silently
re-gathers to full width and the compiled program wastes the mesh
without any test failing.  This level makes that class of silent
regression a ratcheted static gate BEFORE the refactor lands, the
same contract PR 3/6/12 applied to donation bugs, compile explosions,
and concurrency races.

The auditor walks the SAME :class:`~.programspace.Candidate` records
the program-space auditor enumerates (both trainers' step jaxprs, the
streamed-head block programs, the serve predictor's bucket programs),
seeds per-dimension mesh-axis specs on the candidate's inputs, and
abstractly propagates them through every eqn of the traced jaxpr —
no compilation, no chip time.  Three products:

- a per-step **replication ledger**: for every large input buffer
  (params, opt state, activations/data, edge/halo tables) which mesh
  axes it is split over, which it is replicated over, and the
  per-device bytes implied — checked against ``core/memory.py``'s
  plan the way ``hlo_lint`` checks bytes-accessed;
- ratcheted **rules** (shrink-only baseline/pragma contract):

  - ``replication-budget`` — the ledger's total replicated bytes per
    step on the canonical candidate mesh vs the ratcheted
    ``replication_budget`` in ``scripts/lint_baseline.json`` (the
    2-D-mesh analogue of PR 6's ``program_budget``: a PR that adds a
    replicated buffer fails here, and F-sharding work ratchets the
    bound down); plus a loose ledger-vs-plan excess check;
  - ``full-width-materialization`` — ops whose abstract-eval output
    is unsplit along a sharded-input axis (the implicit re-gather);
  - ``sharding-mismatch`` — pjit in/out shardings or
    ``with_sharding_constraint``s that force an implicit
    all-gather/reshard on the hot path;
  - ``donation-under-sharding`` — donated buffers whose donor/donee
    shardings differ, silently voiding the aliasing the PR-3
    donation fixes bought;

- a **mesh-portability report**: the same propagation run against
  *abstract candidate meshes* — the feature dims seeded over the
  future ``model`` axis — enumerating every ``(parts, model)`` shape
  of the 8-virtual-device rig (1x8, 2x4, 4x2, 8x1): which ops are
  already mesh-agnostic, which sites would pin the F axis replicated
  (op, layer, bytes), and the modeled per-device HBM at each shape
  (``core/memory.per_axis_plan_bytes``).  Emitted as ``sharding``
  events and rendered by ``python -m roc_tpu.report --sharding`` —
  the 2-D-mesh PR starts from a machine-checked worklist instead of
  a hunch.

Live-mesh semantics vs simulation: findings come from the LIVE rig
semantics (the real 1-D parts mesh, plus any ``sharding_constraint``
/ pjit sharding the code actually carries — today none, so the
baseline is EMPTY and stays so until the 2-D work begins, exactly
like the compile-explosion ratchet before a new program shape).  The
``model``-axis seeding is confined to the portability REPORT, whose
sites are a migration worklist, not regressions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Set, Tuple)

from ..obs.events import emit
from ..parallel import (MODEL_AXIS, PARTS_AXIS, candidate_mesh_shapes,
                        mesh_axes, model_shard_spec)
from .findings import Finding

SHARDING_RULES = ("replication-budget", "full-width-materialization",
                  "sharding-mismatch", "donation-under-sharding")

# the candidate mesh the replication ratchet is measured on: the
# middle (parts, model) factorization of the 8-virtual-device rig —
# big enough on both axes that "replicated over model" and
# "replicated over parts" both cost real bytes
CANONICAL_SHAPE = (2, 4)

# ledger-vs-plan excess factor (the hlo-bytes-model analogue):
# deliberately loose — the ledger counts live input buffers, the plan
# estimates peak residency; only order-of-magnitude disagreement
# indicates the step holds far more than the plan modeled
PLAN_EXCESS_FACTOR = 4.0

# buffers below this never enter the ledger (rng keys, scalars, tiny
# metadata) — they are noise at every scale the rules care about
LEDGER_MIN_BYTES = 1024

# a "full-width" site must be at least the per-device activation
# block to report: elems >= V*F / total mesh devices

Spec = Tuple[Optional[str], ...]


def _rep(rank: int) -> Spec:
    return (None,) * rank


@dataclass
class Site:
    """One propagation incident: a place where a mesh-axis split dies
    (``full-width`` / ``unknown-op`` / ``boundary``) or two shardings
    disagree (``reshard``)."""

    kind: str
    op: str
    shape: Tuple[int, ...]
    dtype: str
    lost: Tuple[str, ...]
    layer: int
    src: str

    @property
    def elems(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n

    def bytes(self, itemsize: Optional[int] = None) -> int:
        if itemsize is None:
            try:
                import numpy as np
                itemsize = int(np.dtype(self.dtype).itemsize)
            except TypeError:
                itemsize = 4
        return self.elems * itemsize

    @property
    def key(self) -> str:
        return (f"{self.kind}|{self.op}|{self.dtype}"
                f"{list(self.shape)}|{','.join(self.lost)}")

    def record(self, shapes: Sequence[Tuple[int, int]],
               has_vertex_dim: bool) -> Dict[str, Any]:
        """The report/JSON form, with the modeled per-device bytes of
        the materialized tensor at each candidate mesh shape: once
        the split dies, the tensor is full along the lost axis — only
        the surviving vertex split still divides it."""
        per_shape = {}
        for p, m in shapes:
            div = p if has_vertex_dim else 1
            per_shape[f"{p}x{m}"] = self.bytes() // max(div, 1)
        return {"kind": self.kind, "op": self.op,
                "shape": list(self.shape), "dtype": self.dtype,
                "lost": list(self.lost), "layer": self.layer,
                "src": self.src, "bytes": self.bytes(),
                "per_device_bytes": per_shape}


def _src_of(eqn) -> str:
    """Best-effort ``file:line`` of the user frame that traced this
    eqn — informational only (fingerprints never embed it).  Frames
    inside the analysis package are skipped: the auditor's own
    ``make_jaxpr`` call is never the interesting site."""
    try:
        from jax._src import source_info_util
        for frame in source_info_util.user_frames(eqn.source_info):
            fname = str(frame.file_name).replace("\\", "/")
            # only frames of the audited tree count, and never the
            # audit/report entry points themselves — an eqn created
            # by jax machinery with no library frame (the shard_map
            # boundary) reports no site rather than a wrong one
            if ("/roc_tpu/" not in fname or "/analysis/" in fname
                    or fname.endswith("/report.py")):
                continue
            return f"{fname.rsplit('/', 1)[-1]}:{frame.start_line}"
    except Exception:  # noqa: BLE001 - private API, best effort
        pass
    return ""


# ------------------------------------------------------------ engine

# shape-preserving (broadcast-free at the jaxpr level — jax inserts
# explicit broadcast_in_dim) n-ary ops: output spec = join of inputs
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "pow", "atan2", "max", "min",
    "and", "or", "xor", "not", "neg", "sign", "floor", "ceil",
    "round", "exp", "exp2", "expm1", "log", "log1p", "sqrt", "rsqrt",
    "cbrt", "logistic", "tanh", "tan", "sin", "cos", "asin", "acos",
    "atan", "sinh", "cosh", "asinh", "acosh", "atanh", "erf", "erfc",
    "erf_inv", "abs", "convert_element_type", "bitcast_convert_type",
    "is_finite", "eq", "ne", "ge", "gt", "le", "lt", "select_n",
    "clamp", "nextafter", "real", "imag", "conj", "square",
    "reciprocal", "integer_pow", "shift_left",
    "shift_right_logical", "shift_right_arithmetic",
    "population_count", "clz", "copy", "stop_gradient",
    "threefry2x32", "random_bits", "random_wrap", "random_unwrap",
    "random_fold_in", "random_seed", "random_clone", "erf_inv",
}

# spec-transparent containers: propagate into the sub-jaxpr with
# end-aligned invar mapping (handles cond's leading index operand and
# custom_vjp's nondiff prefixes), outputs end-aligned back
_CONTAINER = {"pjit", "closed_call", "core_call", "call", "remat",
              "remat2", "checkpoint", "custom_jvp_call",
              "custom_vjp_call", "custom_jvp_call_jaxpr",
              "custom_vjp_call_jaxpr", "custom_lin"}

# value-preserving collectives: the spec rides through unchanged
_SPEC_KEEP_COLLECTIVES = {"psum", "pmax", "pmin", "ppermute",
                          "psum_invariant", "pbroadcast"}

# known ops whose outputs we simply stop tracking, WITHOUT charging a
# full-width site: index/bookkeeping ops whose outputs are never
# activation-scale in this tree, or ops jax lowers around the hot
# path (rng plumbing, device placement)
_QUIET = {"iota", "rng_bit_generator", "axis_index", "device_put",
          "copy_p", "create_token", "eq_to", "platform_index",
          "top_k", "approx_top_k", "reduce_precision", "nan_to_num",
          "squeeze_shard", "dimension_size"}


class Propagator:
    """Abstract sharding-spec propagation over one ClosedJaxpr.

    ``axis_sizes`` maps mesh-axis name -> size (axes of size 1 are
    still tracked — structure, not arithmetic).  ``scale_elems`` is
    the reporting floor for materialization sites (the per-device
    activation block); spec deaths below it are tracked but not
    reported.  Incidents land in ``self.sites``; per-op preservation
    stats in ``self.ops_total`` / ``self.ops_agnostic``.
    """

    def __init__(self, axis_sizes: Dict[str, int], scale_elems: int,
                 record: bool = True):
        self.axis_sizes = dict(axis_sizes)
        self.scale_elems = max(int(scale_elems), 1)
        self.record = record
        self.sites: List[Site] = []
        self.ops_total = 0
        self.ops_agnostic = 0
        self.layer = 0
        self._site_keys: Set[str] = set()
        # distinct large intermediates seen during the walk — the
        # "activations" rows of the replication ledger: (shape,
        # dtype, spec, inside-shard_map) -> occurrence count
        self.acts: Dict[Tuple, int] = {}
        self._sm_depth = 0

    # ---- bookkeeping

    def _note(self, kind: str, eqn, aval, lost: Iterable[str]) -> None:
        lost = tuple(sorted(set(lost)))
        if not lost or not self.record:
            return
        shape = tuple(int(d) for d in getattr(aval, "shape", ()))
        n = 1
        for d in shape:
            n *= d
        if n < self.scale_elems:
            return
        site = Site(kind=kind, op=eqn.primitive.name, shape=shape,
                    dtype=str(getattr(aval, "dtype", "?")),
                    lost=lost, layer=self.layer, src=_src_of(eqn))
        if site.key not in self._site_keys:
            self._site_keys.add(site.key)
            self.sites.append(site)

    @staticmethod
    def _axes_of(specs: Iterable[Spec]) -> Set[str]:
        return {a for s in specs for a in s if a is not None}

    # ---- spec algebra

    def _join(self, eqn, specs: List[Spec], shapes: List[Tuple[int, ...]]
              ) -> Spec:
        """Trailing-aligned elementwise join: per dim take the agreed
        split; a genuine conflict (two different axes on one dim) is a
        reshard site and resolves to None."""
        rank = max((len(s) for s in shapes), default=0)
        out: List[Optional[str]] = [None] * rank
        for spec, shape in zip(specs, shapes):
            off = rank - len(shape)
            for d, a in enumerate(spec):
                if a is None:
                    continue
                od = off + d
                if out[od] is None:
                    out[od] = a
                elif out[od] != a:
                    self._note("reshard", eqn,
                               eqn.outvars[0].aval, (a, out[od]))
                    out[od] = None
        return tuple(out)

    # ---- main walk

    def run(self, closed_jaxpr, in_specs: Sequence[Spec]
            ) -> List[Spec]:
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        env: Dict[Any, Spec] = {}
        for v, s in zip(jaxpr.invars, in_specs):
            env[v] = tuple(s)
        for v in getattr(jaxpr, "constvars", ()):
            env[v] = _rep(len(getattr(v.aval, "shape", ())))
        self._walk(jaxpr, env)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _read(self, env: Dict[Any, Spec], v) -> Spec:
        if hasattr(v, "val"):          # Literal
            return _rep(len(getattr(getattr(v, "aval", None),
                                    "shape", ())))
        return env.get(v, _rep(len(getattr(v.aval, "shape", ()))))

    def _write(self, env: Dict[Any, Spec], eqn,
               out_specs: Sequence[Optional[Spec]]) -> None:
        for v, s in zip(eqn.outvars, out_specs):
            aval = getattr(v, "aval", None)
            shape = tuple(int(d) for d in getattr(aval, "shape", ()))
            rank = len(shape)
            if s is None:
                s = _rep(rank)
            s = tuple(s)
            if len(s) != rank:      # defensive: never mis-rank a var
                s = _rep(rank)
            env[v] = s
            if self.record and eqn.primitive.name not in _CONTAINER \
                    and eqn.primitive.name != "shard_map":
                n = 1
                for d in shape:
                    n *= d
                if n >= self.scale_elems:
                    key = (shape, str(getattr(aval, "dtype", "?")),
                           s, self._sm_depth > 0)
                    self.acts[key] = self.acts.get(key, 0) + 1

    def _walk(self, jaxpr, env: Dict[Any, Spec]) -> None:
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dot_general":
                out_aval = getattr(eqn.outvars[0], "aval", None)
                n = 1
                for d in getattr(out_aval, "shape", ()):
                    n *= int(d)
                if n >= self.scale_elems:
                    # activation-scale matmul = one layer boundary;
                    # sites report the count as their "layer"
                    self.layer += 1
            specs = [self._read(env, v) for v in eqn.invars]
            shapes = [tuple(int(d) for d in
                            getattr(getattr(v, "aval", None),
                                    "shape", ()))
                      for v in eqn.invars]
            had_split = bool(self._axes_of(specs))
            # containers (pjit/scan/shard_map/...) are wrappers, not
            # ops: their BODIES are walked and counted, and a
            # shard_map boundary pin is already a reported site —
            # charging the wrapper eqn would double-book it
            wrapper = (eqn.primitive.name in _CONTAINER
                       or eqn.primitive.name in ("shard_map", "scan",
                                                 "while", "cond"))
            if not wrapper:
                self.ops_total += 1
            out = self._eqn(eqn, specs, shapes, env)
            self._write(env, eqn, out)
            if wrapper:
                continue
            if had_split:
                kept = self._axes_of(
                    [self._read(env, v) for v in eqn.outvars])
                # agnostic = the splits survived, or the op is a
                # legitimate consumer (reduction/contraction); an op
                # that KILLED a split any other way is the
                # would-replicate population
                if kept or self._consumes(eqn):
                    self.ops_agnostic += 1
            else:
                self.ops_agnostic += 1

    @staticmethod
    def _consumes(eqn) -> bool:
        """True for ops that legitimately consume a split (reductions
        over the split dim, contractions) — losing it there is not a
        portability defect."""
        return eqn.primitive.name in ("reduce_sum", "reduce_max",
                                      "reduce_min", "reduce_prod",
                                      "reduce_and", "reduce_or",
                                      "dot_general", "argmax",
                                      "argmin")

    # ---- per-primitive transfer rules

    def _eqn(self, eqn, specs: List[Spec],
             shapes: List[Tuple[int, ...]], env) -> List[Optional[Spec]]:
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name in _ELEMENTWISE:
            return [self._join(eqn, specs, shapes)] * n_out
        if name == "optimization_barrier":
            return list(specs)[:n_out] + [None] * (n_out - len(specs))
        if name == "dot_general":
            return [self._dot_general(eqn, specs, shapes)]
        if name == "broadcast_in_dim":
            return [self._broadcast(eqn, specs[0])]
        if name == "reshape":
            return [self._reshape(eqn, specs[0], shapes[0])]
        if name == "transpose":
            perm = eqn.params["permutation"]
            return [tuple(specs[0][p] for p in perm)]
        if name == "squeeze":
            drop = set(eqn.params.get("dimensions", ()))
            return [tuple(a for d, a in enumerate(specs[0])
                          if d not in drop)]
        if name == "expand_dims":
            add = set(eqn.params.get("dimensions", ()))
            out_rank = len(specs[0]) + len(add)
            it = iter(specs[0])
            return [tuple(None if d in add else next(it)
                          for d in range(out_rank))]
        if name in ("reduce_sum", "reduce_max", "reduce_min",
                    "reduce_prod", "reduce_and", "reduce_or",
                    "argmax", "argmin"):
            axes = set(eqn.params.get("axes", ()))
            return [tuple(a for d, a in enumerate(specs[0])
                          if d not in axes)] * n_out
        if name in ("cumsum", "cumprod", "cummax", "cummin",
                    "cumlogsumexp"):
            ax = eqn.params.get("axis", 0)
            out = list(specs[0])
            if out[ax] is not None:
                self._note("full-width", eqn, eqn.outvars[0].aval,
                           (out[ax],))
                out[ax] = None
            return [tuple(out)]
        if name == "slice":
            return [self._slice(eqn, specs[0], shapes[0])]
        if name == "dynamic_slice":
            return [self._dynamic_slice(eqn, specs[0], shapes[0])]
        if name == "dynamic_update_slice":
            return [self._dus(eqn, specs, shapes)]
        if name == "gather":
            return [self._gather(eqn, specs, shapes)]
        if name.startswith("scatter"):
            return [self._scatter(eqn, specs, shapes)]
        if name == "concatenate":
            dim = eqn.params["dimension"]
            joined = list(self._join(eqn, specs, shapes))
            if dim < len(joined):
                joined[dim] = None
            return [tuple(joined)]
        if name == "pad":
            cfg = eqn.params.get("padding_config", ())
            out = list(specs[0]) + [None] * (len(cfg) - len(specs[0]))
            for d, (lo, hi, interior) in enumerate(cfg):
                if lo or hi or interior:
                    out[d] = None
            return [tuple(out)]
        if name in ("sort",):
            dim = eqn.params.get("dimension", -1)
            outs = []
            for s in specs[:n_out]:
                o = list(s)
                if o and o[dim] is not None:
                    self._note("full-width", eqn,
                               eqn.outvars[0].aval, (o[dim],))
                if o:
                    o[dim] = None
                outs.append(tuple(o))
            return outs + [None] * (n_out - len(outs))
        if name == "rev":
            return [specs[0]]
        if name == "split":
            ax = eqn.params.get("axis", 0)
            out = list(specs[0])
            if ax < len(out):
                out[ax] = None
            return [tuple(out)] * n_out
        if name == "all_gather":
            dim = eqn.params.get("all_gather_dimension", 0)
            out = list(specs[0])
            ax = eqn.params.get("axis_name")
            axes = ax if isinstance(ax, tuple) else (ax,)
            out = [None if a in axes else a for a in out]
            if dim < len(out):
                out[dim] = None
            return [tuple(out)] * n_out
        if name in _SPEC_KEEP_COLLECTIVES:
            return list(specs)[:n_out] + [None] * (n_out - len(specs))
        if name == "all_to_all":
            return [None] * n_out
        if name == "sharding_constraint":
            return [self._constraint(eqn, specs[0])]
        if name == "shard_map":
            return self._shard_map(eqn, specs, shapes)
        if name == "scan":
            return self._scan(eqn, specs)
        if name == "while":
            return self._while(eqn, specs)
        if name == "cond":
            return self._cond(eqn, specs)
        if name in _CONTAINER:
            return self._container(eqn, specs)
        if name in _QUIET:
            return [None] * n_out
        # unknown primitive holding a split: the exact "single
        # unconstrained op" GSPMD failure mode — the split dies and
        # everything downstream re-gathers to full width
        if self._axes_of(specs):
            for v in eqn.outvars:
                self._note("unknown-op", eqn, v.aval,
                           self._axes_of(specs))
        return [None] * n_out

    def _dot_general(self, eqn, specs, shapes) -> Spec:
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        ls, rs = specs[0], specs[1]
        lfree = [d for d in range(len(shapes[0]))
                 if d not in lc and d not in lb]
        rfree = [d for d in range(len(shapes[1]))
                 if d not in rc and d not in rb]
        out: List[Optional[str]] = []
        for dl, dr in zip(lb, rb):
            a = ls[dl] if ls[dl] is not None else rs[dr]
            out.append(a)
        out.extend(ls[d] for d in lfree)
        out.extend(rs[d] for d in rfree)
        # one axis shards at most one dim: first occurrence wins
        seen: Set[str] = set()
        for i, a in enumerate(out):
            if a is None:
                continue
            if a in seen:
                out[i] = None
            else:
                seen.add(a)
        return tuple(out)

    def _broadcast(self, eqn, spec: Spec) -> Spec:
        bd = eqn.params["broadcast_dimensions"]
        shape = eqn.params["shape"]
        in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        out: List[Optional[str]] = [None] * len(shape)
        for i, od in enumerate(bd):
            if i < len(spec) and in_shape[i] == shape[od]:
                out[od] = spec[i]
        return tuple(out)

    def _reshape(self, eqn, spec: Spec,
                 in_shape: Tuple[int, ...]) -> Spec:
        out_shape = tuple(int(d) for d in eqn.params["new_sizes"])
        out: List[Optional[str]] = [None] * len(out_shape)
        # leading/trailing alignment: dims preserved verbatim keep
        # their spec; anything reshaped through the middle loses it
        i = 0
        while (i < len(in_shape) and i < len(out_shape)
               and in_shape[i] == out_shape[i]):
            if i < len(spec):
                out[i] = spec[i]
            i += 1
        j = 0
        while (j < len(in_shape) - i and j < len(out_shape) - i
               and in_shape[-1 - j] == out_shape[-1 - j]):
            out[len(out_shape) - 1 - j] = spec[len(in_shape) - 1 - j]
            j += 1
        # a merge whose OUTER (major) factor carried the split keeps
        # it on the merged dim (row-major shards stay contiguous)
        lost = {a for d, a in enumerate(spec)
                if a is not None and a not in out}
        for d, a in enumerate(spec):
            if a is None or a in out:
                continue
            if (d < len(in_shape) and i <= d
                    and i < len(out_shape)
                    and out_shape[i] % in_shape[d] == 0
                    and d == i):
                out[i] = a
                lost.discard(a)
        if lost:
            self._note("full-width", eqn, eqn.outvars[0].aval, lost)
        return tuple(out)

    def _slice(self, eqn, spec: Spec,
               in_shape: Tuple[int, ...]) -> Spec:
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        out = list(spec)
        for d, (s, l) in enumerate(zip(starts, limits)):
            if (l - s) != in_shape[d] and out[d] is not None:
                self._note("full-width", eqn, eqn.invars[0].aval,
                           (out[d],))
                out[d] = None
        return tuple(out)

    def _dynamic_slice(self, eqn, spec: Spec,
                       in_shape: Tuple[int, ...]) -> Spec:
        sizes = eqn.params["slice_sizes"]
        out = list(spec)
        for d, sz in enumerate(sizes):
            if sz != in_shape[d] and out[d] is not None:
                self._note("full-width", eqn, eqn.invars[0].aval,
                           (out[d],))
                out[d] = None
        return tuple(out)

    def _dus(self, eqn, specs, shapes) -> Spec:
        op, upd = specs[0], specs[1]
        out = list(op)
        for d in range(min(len(shapes[0]), len(shapes[1]))):
            if shapes[1][d] != shapes[0][d] and out[d] is not None:
                self._note("full-width", eqn, eqn.invars[0].aval,
                           (out[d],))
                out[d] = None
            elif out[d] is None and d < len(upd):
                out[d] = upd[d]
        return tuple(out)

    def _gather(self, eqn, specs, shapes) -> Spec:
        dn = eqn.params["dimension_numbers"]
        sizes = eqn.params["slice_sizes"]
        op_spec, op_shape = specs[0], shapes[0]
        out_rank = len(getattr(eqn.outvars[0].aval, "shape", ()))
        # indexing across a split dim re-gathers the operand
        for d in dn.start_index_map:
            if (d < len(op_spec) and op_spec[d] is not None
                    and sizes[d] != op_shape[d]):
                self._note("full-width", eqn, eqn.invars[0].aval,
                           (op_spec[d],))
        collapsed = set(dn.collapsed_slice_dims)
        window_ops = [d for d in range(len(op_shape))
                      if d not in collapsed]
        out: List[Optional[str]] = [None] * out_rank
        for i, od in enumerate(dn.offset_dims):
            if i < len(window_ops):
                src = window_ops[i]
                if (sizes[src] == op_shape[src]
                        and src < len(op_spec)):
                    out[od] = op_spec[src]
        return tuple(out)

    def _scatter(self, eqn, specs, shapes) -> Spec:
        dn = eqn.params["dimension_numbers"]
        op_spec = list(specs[0])
        upd_spec = specs[2] if len(specs) > 2 else _rep(0)
        for d in dn.scatter_dims_to_operand_dims:
            if d < len(op_spec) and op_spec[d] is not None:
                self._note("full-width", eqn, eqn.invars[0].aval,
                           (op_spec[d],))
                op_spec[d] = None
        inserted = set(dn.inserted_window_dims)
        window_ops = [d for d in range(len(shapes[0]))
                      if d not in inserted]
        for i, ud in enumerate(dn.update_window_dims):
            if i < len(window_ops) and ud < len(upd_spec):
                dst = window_ops[i]
                if op_spec[dst] is None:
                    op_spec[dst] = upd_spec[ud]
        return tuple(op_spec)

    def _constraint(self, eqn, spec: Spec) -> Spec:
        want = _named_sharding_spec(
            eqn.params.get("sharding"),
            len(getattr(eqn.outvars[0].aval, "shape", ())))
        if want is None:
            return spec
        for d, (have, w) in enumerate(zip(spec, want)):
            if have is not None and w != have:
                self._note("reshard", eqn, eqn.invars[0].aval,
                           (have,))
        return want

    def _shard_map(self, eqn, specs, shapes) -> List[Optional[Spec]]:
        body = eqn.params["jaxpr"]
        in_names = eqn.params.get("in_names", ())
        out_names = eqn.params.get("out_names", ())
        body_in: List[Spec] = []
        for i, (spec, names) in enumerate(zip(specs, in_names)):
            names = dict(names or {})
            consumed = {a for axes in names.values() for a in axes}
            inner = []
            for d, a in enumerate(spec):
                if a is None:
                    inner.append(None)
                elif a in (names.get(d) or ()):
                    inner.append(None)        # split consumed locally
                elif a in consumed:
                    inner.append(None)
                else:
                    # the boundary pins this dim replicated: entering
                    # forces an all-gather of the split axis
                    self._note("boundary", eqn,
                               getattr(eqn.invars[i], "aval", None),
                               (a,))
                    inner.append(None)
            body_in.append(tuple(inner))
        self._sm_depth += 1
        try:
            prop_out = self._sub(body, body_in)
        finally:
            self._sm_depth -= 1
        outs: List[Optional[Spec]] = []
        for i, v in enumerate(eqn.outvars):
            names = dict((out_names[i] if i < len(out_names)
                          else {}) or {})
            rank = len(getattr(v.aval, "shape", ()))
            spec = list(prop_out[i] if i < len(prop_out)
                        else _rep(rank))
            spec += [None] * (rank - len(spec))
            for d, axes in names.items():
                if axes and d < rank:
                    spec[d] = axes[0]
            outs.append(tuple(spec[:rank]))
        return outs

    def _scan(self, eqn, specs) -> List[Optional[Spec]]:
        body = eqn.params["jaxpr"]
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        consts, carry, xs = (specs[:nc], specs[nc:nc + ncar],
                             specs[nc + ncar:])
        xs_in: List[Spec] = []
        for s in xs:
            if s and s[0] is not None:
                # scanning over a split dim is a sequential
                # cross-shard walk — the split cannot survive
                self._note("full-width", eqn, eqn.outvars[0].aval
                           if eqn.outvars else None, (s[0],))
            xs_in.append(tuple(s[1:]))
        cur = list(carry)
        for _ in range(2):                      # carry fixpoint
            sub = Propagator(self.axis_sizes, self.scale_elems,
                             record=False)
            out = sub.run(body, list(consts) + cur + xs_in)
            new_carry = [tuple(a if a == b else None
                               for a, b in zip(c, o))
                         if len(c) == len(o) else _rep(len(c))
                         for c, o in zip(cur, out[:ncar])]
            if new_carry == cur:
                break
            cur = new_carry
        out = self._sub(body, list(consts) + cur + xs_in)
        outs: List[Optional[Spec]] = list(out[:ncar])
        for s in out[ncar:]:
            outs.append((None,) + tuple(s))
        return outs

    def _while(self, eqn, specs) -> List[Optional[Spec]]:
        body = eqn.params.get("body_jaxpr")
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        carry = list(specs[cn + bn:])
        consts = list(specs[cn:cn + bn])
        cur = carry
        for _ in range(2):
            sub = Propagator(self.axis_sizes, self.scale_elems,
                             record=False)
            out = sub.run(body, consts + cur)
            new = [tuple(a if a == b else None for a, b in zip(c, o))
                   if len(c) == len(o) else _rep(len(c))
                   for c, o in zip(cur, out)]
            if new == cur:
                break
            cur = new
        return self._sub(body, consts + cur)

    def _cond(self, eqn, specs) -> List[Optional[Spec]]:
        branches = eqn.params.get("branches", ())
        outs: Optional[List[Spec]] = None
        for br in branches:
            got = self._sub(br, specs[1:])
            if outs is None:
                outs = [tuple(s) for s in got]
            else:
                outs = [tuple(a if a == b else None
                              for a, b in zip(x, y))
                        if len(x) == len(y) else _rep(len(x))
                        for x, y in zip(outs, got)]
        return outs or [None] * len(eqn.outvars)

    def _container(self, eqn, specs) -> List[Optional[Spec]]:
        inner = None
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in eqn.params:
                inner = eqn.params[key]
                break
        if inner is None:
            return [None] * len(eqn.outvars)
        body = getattr(inner, "jaxpr", inner)
        n_in = len(body.invars)
        aligned = list(specs)[-n_in:] if n_in else []
        while len(aligned) < n_in:
            aligned.insert(0, _rep(len(getattr(
                body.invars[n_in - len(aligned) - 1].aval,
                "shape", ()))))
        got = self._sub(inner, aligned)
        n_out = len(eqn.outvars)
        got = got[-n_out:] if len(got) >= n_out else got
        return list(got) + [None] * (n_out - len(got))

    def _sub(self, closed_jaxpr, in_specs: Sequence[Spec]
             ) -> List[Spec]:
        """Propagate a sub-jaxpr sharing this propagator's site and
        op accounting."""
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        env: Dict[Any, Spec] = {}
        fixed = []
        for v, s in zip(jaxpr.invars, in_specs):
            rank = len(getattr(v.aval, "shape", ()))
            s = tuple(s)
            fixed.append(s if len(s) == rank else _rep(rank))
        for v, s in zip(jaxpr.invars, fixed):
            env[v] = s
        for v in getattr(jaxpr, "constvars", ()):
            env[v] = _rep(len(getattr(v.aval, "shape", ())))
        self._walk(jaxpr, env)
        return [self._read(env, v) for v in jaxpr.outvars]


def _named_sharding_spec(sharding, rank: int) -> Optional[Spec]:
    """Our per-dim Spec from a jax NamedSharding(-ish) object; None
    when the sharding carries no named spec (unspecified/GSPMD)."""
    pspec = getattr(sharding, "spec", None)
    if pspec is None:
        return None
    out: List[Optional[str]] = []
    try:
        entries = tuple(pspec)
    except TypeError:
        return None
    for e in entries[:rank]:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(str(e[0]) if e else None)
        else:
            out.append(str(e))
    out += [None] * (rank - len(out))
    return tuple(out)


# ------------------------------------------------- seeding + ledger

@dataclass
class RigDims:
    """The semantic dimension vocabulary of one audited rig: which
    sizes mean "vertex axis" and which mean "feature axis" — the
    bridge between raw avals and mesh-axis seeds."""

    vertex_sizes: Set[int]
    feat_sizes: Set[int]
    parts_traced: int = 1        # stacked leading dim of dist data
    scale_elems: int = 1


def rig_dims(tr, ds) -> RigDims:
    """Derive the vocabulary from a built trainer + dataset: vertex
    sizes from the dataset/partition plan, feature sizes from the
    parameter matrices (class width excluded — the C axis stays
    replicated by design, it is F/H parallelism under audit)."""
    import jax
    V = int(ds.graph.num_nodes)
    C = int(ds.num_classes)
    vs = {V, V + 1}    # +1: dummy-row variants (propagation tables)
    parts = 1
    pg = getattr(tr, "pg", None)
    if pg is not None:
        parts = int(pg.num_parts)
        vs.update({int(pg.part_nodes),
                   int(parts * pg.part_nodes),
                   int(parts * pg.part_nodes + 1)})
    fh = getattr(tr, "feats_host", None)
    if fh is not None:
        vs.add(int(fh.shape[0]))
    feats: Set[int] = set()
    for leaf in jax.tree_util.tree_leaves(tr.params):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1:
            feats.update(int(d) for d in shape)
    feats -= {C}
    feats = {d for d in feats if d >= 8}
    F = max(feats) if feats else 1
    return RigDims(vertex_sizes=vs, feat_sizes=feats,
                   parts_traced=parts,
                   scale_elems=max(V * F // 8, 1))


def seed_leaf(shape: Tuple[int, ...], role: str, dims: RigDims,
              model_axis: bool) -> Spec:
    """Per-dimension mesh-axis seed for one input buffer.

    Live semantics: only the dist rigs' stacked leading dim carries
    ``parts`` (the mesh that actually exists).  Portability
    simulation (``model_axis=True``) additionally seeds the LAST
    feature-sized dim of float buffers over ``model`` — the 2-D
    design's feature shards — matching at most one dim per axis."""
    spec: List[Optional[str]] = [None] * len(shape)
    if (dims.parts_traced > 1 and role in ("data", "tables")
            and shape and int(shape[0]) == dims.parts_traced):
        spec[0] = PARTS_AXIS
    if model_axis:
        for d in range(len(shape) - 1, -1, -1):
            if spec[d] is None and int(shape[d]) in dims.feat_sizes:
                spec[d] = MODEL_AXIS
                break
    return tuple(spec)


def _leaf_roles(cand) -> List[Tuple[Any, str]]:
    """(leaf, role) per flattened arg leaf, aligned with the traced
    jaxpr's invars (make_jaxpr flattens the same way)."""
    import jax
    out: List[Tuple[Any, str]] = []
    roles = cand.roles or ("other",) * len(cand.args)
    for arg, role in zip(cand.args, roles):
        for leaf in jax.tree_util.tree_leaves(arg):
            out.append((leaf, role))
    return out


def _leaf_bytes(leaf) -> int:
    import numpy as np
    shape = tuple(getattr(leaf, "shape", ()))
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(np.dtype(getattr(leaf, "dtype", "float32")).itemsize)


def ledger_entries(cand, dims: RigDims,
                   shape: Tuple[int, int]) -> List[Dict[str, Any]]:
    """The replication ledger of one candidate program on one
    ``(parts, model)`` mesh shape, as it stands TODAY: the vertex
    axis is genuinely sharded (the partitioner/shard_map machinery
    exists), and params/opt-state/stream buffers with a
    ``model``-divisible dim are F-sharded at rest (the
    ``put_replicated``/jit-shardings path).  Graph data and the
    feature-less dispatch tables remain replicated over ``model`` —
    the permanent residents of that column.  Sorted largest-first."""
    parts, model = int(shape[0]), int(shape[1])
    out: List[Dict[str, Any]] = []
    for leaf, role in _leaf_roles(cand):
        lshape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        nbytes = _leaf_bytes(leaf)
        if nbytes < LEDGER_MIN_BYTES:
            continue
        has_vertex = (any(d in dims.vertex_sizes for d in lshape)
                      or (dims.parts_traced > 1 and lshape
                          and lshape[0] == dims.parts_traced))
        split, replicated = [], []
        div = 1
        if parts > 1:
            if has_vertex and role in ("data", "tables"):
                split.append(PARTS_AXIS)
                div *= parts
            else:
                replicated.append(PARTS_AXIS)
        if model > 1:
            # params / opt moments / the streamed-head handoff are
            # model-sharded at rest when a dim divides; everything
            # else (graph data, dispatch tables) stays replicated
            mspec = (model_shard_spec(lshape, model)
                     if role in ("params", "opt_state", "stream")
                     else None)
            if mspec is not None:
                split.append(MODEL_AXIS)
                div *= model
            else:
                replicated.append(MODEL_AXIS)
        out.append({
            "role": role,
            "shape": list(lshape),
            "dtype": str(getattr(leaf, "dtype", "?")),
            "bytes": nbytes,
            "split": split,
            "replicated": replicated,
            "per_device_bytes": nbytes // div,
        })
    out.sort(key=lambda e: (-e["bytes"], e["role"], str(e["shape"])))
    return out


def activation_entries(acts: Dict[Tuple, int], dims: RigDims,
                       shape: Tuple[int, int]) -> List[Dict[str, Any]]:
    """Ledger rows for the large INTERMEDIATES the live propagation
    saw (distinct shape/dtype/spec) — the ``[V_p, F]`` activations
    the ROADMAP names.  A tensor living inside a shard_map body is
    per-shard by construction (split over parts); everything is
    replicated over ``model`` today, same convention as the input
    rows."""
    import numpy as np
    parts, model = int(shape[0]), int(shape[1])
    out: List[Dict[str, Any]] = []
    for (tshape, dtype, spec, in_sm), count in acts.items():
        try:
            itemsize = int(np.dtype(dtype).itemsize)
        except TypeError:
            itemsize = 4
        n = 1
        for d in tshape:
            n *= int(d)
        nbytes = n * itemsize
        if nbytes < LEDGER_MIN_BYTES:
            continue
        has_vertex = any(d in dims.vertex_sizes for d in tshape)
        split, replicated = [], []
        div = 1
        if parts > 1:
            if in_sm or has_vertex:
                split.append(PARTS_AXIS)
                div *= parts
            else:
                replicated.append(PARTS_AXIS)
        if model > 1:
            replicated.append(MODEL_AXIS)
        out.append({
            "role": "activations", "shape": list(tshape),
            "dtype": dtype, "bytes": nbytes, "count": count,
            "split": split, "replicated": replicated,
            "per_device_bytes": nbytes // div,
        })
    return out


def union_ledger(per_cand: List[List[Dict[str, Any]]]
                 ) -> List[Dict[str, Any]]:
    """One ledger for the whole step lifecycle: candidates share
    buffers (params appear in train AND eval), so distinct
    ``(role, shape, dtype)`` triples are counted once, largest
    first."""
    seen: Set[Tuple] = set()
    out: List[Dict[str, Any]] = []
    for entries in per_cand:
        for e in entries:
            key = (e["role"], tuple(e["shape"]), e["dtype"],
                   tuple(e["split"]), tuple(e["replicated"]))
            if key in seen:
                continue
            seen.add(key)
            out.append(e)
    out.sort(key=lambda e: (-e["bytes"], e["role"], str(e["shape"])))
    return out


def replicated_bytes(entries: List[Dict[str, Any]]) -> int:
    """The ratchet quantity: per-device bytes of every ledger buffer
    replicated over at least one >1 mesh axis — the bytes the 2-D
    mesh exists to reclaim.  Static shapes only, so the number is
    bit-reproducible across runs."""
    return sum(e["per_device_bytes"] for e in entries
               if e["replicated"])


# ------------------------------------------------------------- rules

def check_replication_budget(config: str, measured: int,
                             budget: Optional[int]) -> List[Finding]:
    """[replication-budget] the ledger's replicated bytes per step on
    the canonical candidate mesh exceed the baselined bound
    (``replication_budget`` in scripts/lint_baseline.json,
    shrink-only).  None = no bound recorded yet — the CLI notes it
    and ``--update-baseline`` initializes it."""
    if budget is None or measured <= budget:
        return []
    return [Finding(
        "replication-budget", f"sharding:{config}",
        f"{measured} replicated bytes/step on the "
        f"{CANONICAL_SHAPE[0]}x{CANONICAL_SHAPE[1]} candidate mesh "
        f"exceed the baselined bound {budget} — a new replicated "
        f"buffer entered this config; shard it (or ratchet "
        f"deliberately by hand-editing replication_budget)",
        key="over-budget",
        detail={"replicated_bytes": measured, "budget": budget})]


def check_plan_excess(config: str, ledger_per_device: int,
                      plan_bytes: Optional[int],
                      factor: float = PLAN_EXCESS_FACTOR
                      ) -> List[Finding]:
    """[replication-budget] (key=plan-excess) the ledger's per-device
    residency exceeds ``factor`` x the core/memory.py plan estimate —
    the step holds far more live bytes than the plan modeled, the
    ledger analogue of hlo-bytes-model."""
    if not plan_bytes or ledger_per_device <= factor * plan_bytes:
        return []
    return [Finding(
        "replication-budget", f"sharding:{config}",
        f"ledger per-device bytes {ledger_per_device} exceed "
        f"{factor:g}x the core/memory.py plan estimate "
        f"({plan_bytes} B) — the step's resident buffers blew past "
        f"the plan",
        key="plan-excess",
        detail={"ledger_per_device": ledger_per_device,
                "plan_bytes": plan_bytes, "factor": factor})]


def findings_from_sites(config: str, slot: str,
                        sites: List[Site]) -> List[Finding]:
    """Map live-semantics propagation incidents to findings:
    full-width/unknown-op/boundary -> full-width-materialization,
    reshard -> sharding-mismatch."""
    out: List[Finding] = []
    unit = f"sharding:{config}:{slot}"
    for s in sites:
        if s.kind == "reshard":
            out.append(Finding(
                "sharding-mismatch", unit,
                f"{s.op} forces an implicit reshard of "
                f"{s.dtype}{list(s.shape)} (axes {', '.join(s.lost)} "
                f"disagree) on the hot path"
                + (f" [{s.src}]" if s.src else ""),
                key=s.key))
        else:
            out.append(Finding(
                "full-width-materialization", unit,
                f"{s.op} loses the {'/'.join(s.lost)} split of "
                f"{s.dtype}{list(s.shape)} (layer {s.layer}) — the "
                f"output re-gathers to full width"
                + (f" [{s.src}]" if s.src else ""),
                key=s.key))
    return out


def check_donation(config: str, cand, in_specs: List[Spec],
                   out_specs: List[Spec], jaxpr) -> List[Finding]:
    """[donation-under-sharding] a donated input whose matching
    output carries a different propagated sharding: XLA only aliases
    buffers with identical layouts, so the donation silently degrades
    to a copy — doubling residency exactly where the donation fixes
    (PR 3) reclaimed it."""
    import jax
    out: List[Finding] = []
    if not cand.donate:
        return out
    flat_specs: List[Tuple[Any, Spec, int]] = []   # (leaf, spec, arg)
    idx = 0
    for ai, arg in enumerate(cand.args):
        for leaf in jax.tree_util.tree_leaves(arg):
            flat_specs.append((leaf, in_specs[idx], ai))
            idx += 1
    out_sigs = []
    for v, spec in zip(jaxpr.jaxpr.outvars, out_specs):
        a = getattr(v, "aval", None)
        if a is not None:
            out_sigs.append((tuple(a.shape), str(a.dtype), spec))
    for leaf, spec, ai in flat_specs:
        if ai not in cand.donate:
            continue
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", "?"))
        if _leaf_bytes(leaf) < LEDGER_MIN_BYTES:
            continue
        matches = [s for (sh, dt, s) in out_sigs
                   if sh == shape and dt == dtype]
        if not matches or any(tuple(m) == tuple(spec)
                              for m in matches):
            continue
        out.append(Finding(
            "donation-under-sharding", f"sharding:{config}:{cand.slot}",
            f"donated arg {ai} ({dtype}{list(shape)}, spec "
            f"{list(spec)}) only matches outputs with different "
            f"sharding ({[list(m) for m in matches[:2]]}) — the "
            f"donation degrades to a copy under sharding",
            key=f"donate|{ai}|{dtype}{list(shape)}"))
    return out


# -------------------------------------------------------- rig audit

def audit_candidate(config: str, cand, dims: RigDims,
                    select: Optional[List[str]]
                    ) -> Tuple[List[Finding], Dict[str, Any],
                               Dict[Tuple, int]]:
    """One candidate program: live-semantics findings, the
    portability record (model-axis simulation), and the live walk's
    large-intermediate census (the ledger's activation rows)."""
    import jax
    findings: List[Finding] = []
    jaxpr = jax.make_jaxpr(cand.fn)(*cand.args)
    axes = {PARTS_AXIS: dims.parts_traced, MODEL_AXIS: 1}

    live_in = [seed_leaf(tuple(getattr(leaf, "shape", ())), role,
                         dims, model_axis=False)
               for leaf, role in _leaf_roles(cand)]
    live = Propagator(axes, dims.scale_elems)
    live_out = live.run(jaxpr, live_in)
    if select is None or "full-width-materialization" in select \
            or "sharding-mismatch" in select:
        fs = findings_from_sites(config, cand.slot, live.sites)
        if select is not None:
            fs = [f for f in fs if f.rule in select]
        findings.extend(fs)
    if select is None or "donation-under-sharding" in select:
        findings.extend(check_donation(config, cand, live_in,
                                       live_out, jaxpr))

    sim_in = [seed_leaf(tuple(getattr(leaf, "shape", ())), role,
                        dims, model_axis=True)
              for leaf, role in _leaf_roles(cand)]
    sim = Propagator(mesh_axes(CANONICAL_SHAPE), dims.scale_elems)
    sim.run(jaxpr, sim_in)
    record = {
        "slot": cand.slot,
        "ops": sim.ops_total,
        "mesh_agnostic_ops": sim.ops_agnostic,
        "sites": [s.record(candidate_mesh_shapes(),
                           has_vertex_dim=any(
                               d in dims.vertex_sizes
                               for d in s.shape))
                  for s in sim.sites],
    }
    return findings, record, live.acts


def audit_rig(name: str, spec, tr, ds,
              budget: Optional[int],
              select: Optional[List[str]]
              ) -> Tuple[List[Finding], Dict[str, Any]]:
    from ..core.memory import per_axis_plan_bytes
    from .programspace import candidate_programs
    dims = rig_dims(tr, ds)
    findings: List[Finding] = []
    cands = candidate_programs(tr)
    slots: List[Dict[str, Any]] = []
    all_acts: Dict[Tuple, int] = {}
    for cand in cands:
        fs, rec, acts = audit_candidate(name, cand, dims, select)
        findings.extend(fs)
        slots.append(rec)
        for k, n in acts.items():
            all_acts[k] = all_acts.get(k, 0) + n

    # ONE ledger for the step lifecycle: distinct input buffers
    # across every candidate (params appear once, not per slot) plus
    # the distinct large intermediates the live walk saw
    entries = union_ledger(
        [ledger_entries(c, dims, CANONICAL_SHAPE) for c in cands]
        + [activation_entries(all_acts, dims, CANONICAL_SHAPE)])
    measured = replicated_bytes(entries)
    live_shape = (dims.parts_traced, 1)
    live_entries = union_ledger(
        [ledger_entries(c, dims, live_shape) for c in cands]
        + [activation_entries(all_acts, dims, live_shape)])
    ledger_per_device = sum(e["per_device_bytes"]
                            for e in live_entries)
    plan_bytes = getattr(tr, "_modeled_bytes", None)
    if select is None or "replication-budget" in select:
        findings.extend(check_replication_budget(name, measured,
                                                 budget))
        findings.extend(check_plan_excess(name, ledger_per_device,
                                          plan_bytes))

    # mesh-portability: modeled per-device HBM at every (parts,
    # model) shape of the rig, from the planner's per-axis model
    layer_dims = _layer_dims_of(tr, ds)
    shapes = []
    for p, m in candidate_mesh_shapes():
        ax = per_axis_plan_bytes(
            int(ds.graph.num_nodes), int(ds.graph.num_edges),
            layer_dims,
            parts=p, model=m,
            halo=getattr(tr.config, "halo", "gather"),
            features=getattr(tr.config, "features", "hbm"),
            remat=bool(getattr(tr.config, "remat", False)))
        shapes.append({"parts": p, "model": m,
                       "per_device_bytes": ax["total"]["per_device"],
                       "components": {
                           k: {"per_device": v["per_device"],
                               "replicated": v.get("replicated", [])}
                           for k, v in ax.items() if k != "total"}})

    n_sites = sum(len(s["sites"]) for s in slots)
    report = {
        "config": name,
        "parts": dims.parts_traced,
        "canonical_shape": list(CANONICAL_SHAPE),
        "replicated_bytes": measured,
        "budget": budget,
        "ledger_per_device_bytes": ledger_per_device,
        "plan_bytes": plan_bytes,
        "ledger": entries[:16],
        "slots": slots,
        "full_width_sites": n_sites,
        "mesh_shapes": shapes,
    }
    if budget is not None:
        report["delta"] = measured - budget
    return findings, report


def _layer_dims_of(tr, ds) -> List[int]:
    """CLI-style layer dims for the plan model, reconstructed from
    the parameter matrices (in-dim, hiddens..., classes) — coarse on
    MLP-per-layer models, which is fine: the plan model itself is
    coarse by design."""
    import jax
    C = int(ds.num_classes)
    F = int(ds.in_dim)
    mats = [tuple(int(d) for d in leaf.shape)
            for leaf in jax.tree_util.tree_leaves(tr.params)
            if len(getattr(leaf, "shape", ())) == 2]
    hiddens = sorted({s[1] for s in mats} - {C, F})
    return [F] + hiddens + [C]


# ------------------------------------------------------------ stage

def audit_sharding(select: Optional[List[str]] = None,
                   replication_budget: Optional[Dict[str, int]] = None,
                   extras: Optional[Dict[str, Any]] = None
                   ) -> List[Finding]:
    """Level-seven entry point: audit every rig config the backend
    can host (the same registry the program-space auditor walks).
    Emits one ``sharding`` event per config; when ``extras`` is a
    dict, appends the report records under ``extras['sharding']``."""
    import jax

    budget = replication_budget or {}
    findings: List[Finding] = []
    ds = None
    from .programspace import build_rig_dataset, build_rig_trainer, \
        rig_configs, rig_required_devices
    for name, spec in rig_configs().items():
        if rig_required_devices(spec) > len(jax.devices()):
            continue
        if ds is None:
            ds = build_rig_dataset()
        tr = build_rig_trainer(spec, ds)
        fs, report = audit_rig(name, spec, tr, ds,
                               budget=budget.get(name),
                               select=select)
        findings.extend(fs)
        emit("sharding",
             f"sharding audit {name}: {report['replicated_bytes']} "
             f"replicated B/step on "
             f"{CANONICAL_SHAPE[0]}x{CANONICAL_SHAPE[1]} (baseline "
             f"{report['budget']}), {report['full_width_sites']} "
             f"full-width site(s) in the portability sim",
             console=False,
             **{k: v for k, v in report.items()
                if k not in ("ledger", "slots", "mesh_shapes")},
             sites=[s for slot in report["slots"]
                    for s in slot["sites"]],
             mesh_shapes=report["mesh_shapes"])
        if extras is not None:
            extras.setdefault("sharding", []).append(report)
    return findings
