"""SPMD collective verifier: static deadlock/consistency rules over
the distributed steps' jaxprs and the ring/halo subroutines.

The distributed layer's failure modes only manifest at P>=2 on real
hardware — a malformed ``ppermute`` permutation hangs the ICI ring, a
collective issued in one branch of a conditional but not the other
desynchronizes the lockstep SPMD programs into a deadlock, a ring
table that disagrees with the partition plan's halo stats silently
aggregates the wrong rows.  None of these raise at trace time.  This
level checks them on the CPU rig, before any chip run:

- [collective-ppermute-cycle] every ``ppermute`` permutation must be a
  single cycle covering the full ``parts`` axis — exactly the named
  hop schedule ``parallel/ring.ring_hop_perm``.  A two-cycle rotates
  two disjoint sub-rings (each shard sees only half the graph); a
  partial cover leaves devices waiting on sends that never come.
- [collective-axis-name] every ``psum`` / ``all_gather`` /
  ``ppermute`` axis name must exist on the mesh the rig built
  (``parallel/distributed.PARTS_AXIS``).  Inside ``shard_map`` a bad
  name is a trace error; the hazard is collectives built from config
  strings that only bind on a larger mesh.
- [collective-conditional] the collective sequence (primitive, axis
  names, operand shape) must be identical across all branches of
  every ``cond`` — a conditional collective is an instant P>=2 hang
  when shards disagree on the predicate (lockstep-SPMD deadlock
  freedom).  Collectives under ``cond`` are fine when every branch
  issues the SAME sequence.
- [collective-ring-halo] the ring tables' real send/recv row counts
  must match the partition plan's halo-in/out stats
  (``core/costmodel.partition_halo_stats`` — the numbers recorded in
  the run manifest): a drifted table build would exchange the wrong
  rows with no shape error anywhere.

Units are :class:`CollectiveUnit` (a traced ClosedJaxpr + the mesh
axis vocabulary); the ring-halo rule is structural (host arrays, no
jaxpr).  Findings ride the same baseline ratchet as every level.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .findings import Finding
from .jaxpr_lint import _aval, _shape_str, iter_eqns

# collectives whose axis names the verifier vets; reduce_* carry
# positional int axes in the same 'axes' param slot, so names are
# filtered to strings below
_COLLECTIVE_PRIMS = ("psum", "pmax", "pmin", "ppermute", "all_gather",
                     "all_gather_invariant", "all_to_all",
                     "reduce_scatter", "axis_index", "pbroadcast")


@dataclass
class CollectiveUnit:
    """One traced distributed program under verification.

    ``axis_sizes`` is the mesh vocabulary the rig actually built
    (name -> size) — the ground truth the axis-name and cycle rules
    hold the traced eqns against."""

    name: str
    jaxpr: Any
    axis_sizes: Dict[str, int] = field(default_factory=dict)

    @property
    def unit(self) -> str:
        return f"collective:{self.name}"


def _axis_names(eqn) -> List[str]:
    """String axis names a collective eqn binds (positional int axes
    of plain reductions are not mesh names and are skipped)."""
    names: List[str] = []
    for param in ("axis_name", "axes"):
        v = eqn.params.get(param)
        if v is None:
            continue
        for a in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(a, str):
                names.append(a)
    return names


def _is_collective(eqn) -> bool:
    return (eqn.primitive.name in _COLLECTIVE_PRIMS
            and bool(_axis_names(eqn)))


def check_ppermute_cycle(u: CollectiveUnit) -> List[Finding]:
    """[collective-ppermute-cycle] see module docstring.  The check is
    against the axis size, not against ring_hop_perm literally — any
    single full cycle is deadlock-free (a reversed ring is legal), but
    the canonical schedule is the one the ring emits."""
    out: List[Finding] = []
    for eqn in iter_eqns(u.jaxpr):
        if eqn.primitive.name != "ppermute":
            continue
        names = _axis_names(eqn)
        perm = [(int(s), int(d)) for s, d in eqn.params.get("perm", ())]
        size = max((u.axis_sizes.get(n, 0) for n in names), default=0)
        if not size:
            continue  # unknown axis: collective-axis-name's business
        problem = _cycle_problem(perm, size)
        if problem:
            out.append(Finding(
                "collective-ppermute-cycle", u.unit,
                f"ppermute over {'/'.join(names)} (size {size}) is "
                f"not a single full cycle: {problem} — this hangs or "
                f"drops shards at P>=2 (the named schedule is "
                f"parallel/ring.ring_hop_perm)",
                key=f"ppermute|{'/'.join(names)}|{problem}"))
    return out


def _cycle_problem(perm: List[Tuple[int, int]],
                   size: int) -> Optional[str]:
    """None when ``perm`` is one cycle covering {0..size-1}; else a
    short description of the defect."""
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    members = set(range(size))
    if set(srcs) != members or set(dsts) != members:
        missing = sorted(members - set(srcs) - set(dsts))
        return (f"covers {len(set(srcs) | set(dsts))}/{size} members"
                + (f" (missing {missing})" if missing else
                   " asymmetrically"))
    if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
        return "duplicate senders/receivers"
    nxt = dict(perm)
    seen, cur = 1, nxt[0]
    while cur != 0 and seen <= size:
        cur = nxt[cur]
        seen += 1
    if seen != size:
        return f"{_n_cycles(nxt, size)} disjoint cycles"
    return None


def _n_cycles(nxt: Dict[int, int], size: int) -> int:
    left, n = set(range(size)), 0
    while left:
        n += 1
        cur = start = left.pop()
        while nxt[cur] != start:
            cur = nxt[cur]
            left.discard(cur)
    return n


def check_axis_names(u: CollectiveUnit) -> List[Finding]:
    """[collective-axis-name] see module docstring."""
    out: List[Finding] = []
    known = set(u.axis_sizes)
    for eqn in iter_eqns(u.jaxpr):
        if eqn.primitive.name not in _COLLECTIVE_PRIMS:
            continue
        for name in _axis_names(eqn):
            if name not in known:
                out.append(Finding(
                    "collective-axis-name", u.unit,
                    f"{eqn.primitive.name} over axis {name!r} which "
                    f"the rig mesh does not define (axes: "
                    f"{sorted(known)}) — binds only on a larger mesh, "
                    f"or never",
                    key=f"axis|{eqn.primitive.name}|{name}"))
    return out


def _collective_signature(jaxpr) -> Tuple:
    """Ordered tuple of (primitive, axis names, operand shape,
    pairing) for every collective in ``jaxpr``, depth-first across
    nesting — the lockstep schedule a branch would execute.  The
    pairing term is the ``perm`` of a ppermute: two branches
    permuting over the same axis with DIFFERENT permutations are just
    as deadlock-prone as psum-vs-nothing (device A sends along one
    schedule while B waits on the other), so the perm is part of the
    sequence identity."""
    sig = []
    for eqn in iter_eqns(jaxpr):
        if not _is_collective(eqn) or eqn.primitive.name == "axis_index":
            continue
        a = _aval(eqn.invars[0]) if eqn.invars else None
        perm = tuple((int(s), int(d))
                     for s, d in eqn.params.get("perm", ()))
        sig.append((eqn.primitive.name, tuple(_axis_names(eqn)),
                    _shape_str(a) if a is not None else "?", perm))
    return tuple(sig)


class _Closed:
    """Minimal ClosedJaxpr-shaped wrapper so iter_eqns accepts a raw
    branch Jaxpr."""

    def __init__(self, jaxpr):
        self.jaxpr = jaxpr


def check_conditional_collective(u: CollectiveUnit) -> List[Finding]:
    """[collective-conditional] see module docstring."""
    out: List[Finding] = []
    for eqn in iter_eqns(u.jaxpr):
        if eqn.primitive.name != "cond":
            continue
        branches = eqn.params.get("branches", ())
        sigs = []
        for br in branches:
            j = getattr(br, "jaxpr", br)
            sigs.append(_collective_signature(_Closed(j)))
        if len(set(sigs)) <= 1:
            continue
        detail = " vs ".join(
            "[" + ", ".join(
                f"{p}@{'/'.join(n)}" + (f"{list(pm)}" if pm else "")
                for p, n, _, pm in s)
            + "]" for s in sigs)
        out.append(Finding(
            "collective-conditional", u.unit,
            f"cond branches issue different collective sequences "
            f"({detail}) — shards disagreeing on the predicate "
            f"deadlock the lockstep SPMD program at P>=2; hoist the "
            f"collective out of the conditional",
            key=f"cond|{detail[:80]}"))
    return out


COLLECTIVE_RULES = {
    "collective-ppermute-cycle": check_ppermute_cycle,
    "collective-axis-name": check_axis_names,
    "collective-conditional": check_conditional_collective,
}


def run_collective_lint(units: Sequence[CollectiveUnit],
                        select: Optional[List[str]] = None
                        ) -> List[Finding]:
    findings: List[Finding] = []
    for unit in units:
        for name, rule in COLLECTIVE_RULES.items():
            if select is not None and name not in select:
                continue
            findings.extend(rule(unit))
    return findings


# ------------------------------------------- ring-table consistency

def ring_table_halo_counts(pg, rt) -> Tuple[np.ndarray, np.ndarray]:
    """(send_in [P], send_out [P]) derived from the RING TABLES alone:
    per part, the distinct external source rows its pairs actually
    gather (what the rotation must deliver to it) and the distinct
    local rows other parts' pairs reference (what it must send).
    Compared against the plan-derived
    ``core/costmodel.partition_halo_stats`` by
    :func:`check_ring_halo` — two independent derivations of the same
    exchange, so a drift in either build is caught."""
    P = pg.num_parts
    recv = np.zeros(P, dtype=np.int64)
    sent: List[set] = [set() for _ in range(P)]
    for p in range(P):
        gathered = set()
        for s in range(P):
            src = np.asarray(rt.src[p, s], dtype=np.int64)
            real = np.unique(src[src < pg.part_nodes])
            if s != p:
                gathered.update((s, int(v)) for v in real)
                sent[s].update(int(v) for v in real)
        recv[p] = len(gathered)
    send = np.array([len(s) for s in sent], dtype=np.int64)
    return recv, send


def check_ring_halo(unit: str, pg, rt) -> List[Finding]:
    """[collective-ring-halo] see module docstring."""
    from ..core.costmodel import partition_halo_stats
    halo_in, halo_out = partition_halo_stats(pg)
    recv, send = ring_table_halo_counts(pg, rt)
    out: List[Finding] = []
    for p in range(pg.num_parts):
        if int(recv[p]) != int(halo_in[p]):
            out.append(Finding(
                "collective-ring-halo", unit,
                f"part {p}: ring tables gather {int(recv[p])} distinct "
                f"external rows but the partition plan's halo-in is "
                f"{int(halo_in[p])} — the hop schedule and the split "
                f"disagree about what must be exchanged",
                key=f"halo-in|part={p}",
                detail={"table": int(recv[p]),
                        "plan": int(halo_in[p])}))
        if int(send[p]) != int(halo_out[p]):
            out.append(Finding(
                "collective-ring-halo", unit,
                f"part {p}: ring tables reference {int(send[p])} "
                f"distinct rows of this part from other parts but the "
                f"plan's halo-out is {int(halo_out[p])}",
                key=f"halo-out|part={p}",
                detail={"table": int(send[p]),
                        "plan": int(halo_out[p])}))
    return out
