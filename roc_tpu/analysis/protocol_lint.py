"""roc-lint level eight: protocol auditor for the serve/checkpoint
state machines.

The serve tier speaks a line-JSON wire protocol (router ↔ replica)
and the checkpoint tier runs a two-phase commit; both are distributed
protocols — a wire vocabulary plus a state machine plus a
crash-consistency argument — and every remaining ROADMAP item (live
rollout, the autoscaler, elastic resize) extends them.  This level
keeps the three legs of that argument in lock-step:

1. **Extraction** (this module): walk the AST of the five protocol
   modules and recover the ACTUAL protocol — every ``{"kind": ...}``
   literal put on a wire via ``*.send(...)`` (one level of helper
   resolution covers ``wire.send(_error_payload(...))``), every
   ``msg.get("kind")`` comparison a receiver dispatches on, the
   per-send-site field sets, and the declared lifecycle/commit
   transition sites.
2. **Declaration** (:mod:`protocol_specs`): the spec tables.  Any
   disagreement with extraction is a finding — the spec is the
   extension point future PRs must edit FIRST.
3. **Exhaustion** (:mod:`modelcheck`): bounded explicit-state BFS over
   the three protocol models; an invariant violation or an
   unexplorable model is a finding.

Rules (all under the shrink-only baseline / ``roc-lint: ok=<rule>``
pragma contract; pure AST + pure-Python BFS — no jax, milliseconds):

``wire-vocabulary``
    a kind is sent with no receiver branch (the receiver would treat
    it as noise — or worse, as a request), a handled kind is never
    sent (dead vocabulary, unless the spec sanctions it with
    ``sent: False``), or a kind-dispatching receiver has no explicit
    unknown-kind rejection (the replica:146 bug class this level
    fixed on landing).
``wire-field-contract``
    a send site omits a field the spec requires for its kind, or
    carries a field the spec does not declare.
``protocol-spec-drift``
    spec and code disagree: a declared kind is never sent/handled, an
    observed kind is undeclared, a declared transition site no longer
    exists, or the model checker's invariant set drifted from
    ``MODEL_INVARIANTS``.
``modelcheck-invariant``
    a model's exhaustive exploration found an invariant violation
    (the finding carries the counterexample schedule), or exhausted
    its state budget (an unexplorable model is a broken tripwire).
``ckpt-commit-order``
    within one function, the checkpoint manifest is published before
    a shard rename — migrated here from concurrency_lint (PR 15) as
    the one source of truth; the callee vocabulary lives in
    :mod:`protocol_specs`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from . import protocol_specs as specs
from .ast_lint import pragma_ok
from .concurrency_lint import (TreeModel, ModuleModel, FuncDef,
                               _call_name, _walk_own)
from .findings import Finding
from .modelcheck import ModelReport, STATE_BUDGET, check_all

PROTOCOL_RULES = (
    "wire-vocabulary",
    "wire-field-contract",
    "protocol-spec-drift",
    "modelcheck-invariant",
    "ckpt-commit-order",
)


# ----------------------------------------------------------- extraction

@dataclass
class SendSite:
    """One ``*.send({...})`` call putting a kind on the wire.
    ``fields`` is None when the payload's keys are not statically
    resolvable (computed keys / ``**`` expansion)."""
    module: str
    func: str
    kind: str
    fields: Optional[Tuple[str, ...]]
    line: int


@dataclass
class HandleSite:
    """One receiver-side comparison against ``msg.get("kind")``."""
    module: str
    func: str
    kind: str
    line: int


@dataclass
class Dispatcher:
    """A receiver function that dispatches on kinds; ``rejects`` is
    True when it explicitly rejects unknown kinds (a ``!=``/``not
    in`` guard with a body, or an ``==`` chain with a final else)."""
    module: str
    func: str
    line: int
    rejects: bool


def _dict_kind_fields(node: ast.AST
                      ) -> Optional[Tuple[str, Optional[Tuple[str, ...]]]]:
    """(kind, field names) for a dict literal with a constant
    ``"kind"`` entry; fields None when any key is computed."""
    if not isinstance(node, ast.Dict):
        return None
    kind = None
    fields: List[str] = []
    resolvable = True
    for k, v in zip(node.keys, node.values):
        if k is None or not isinstance(k, ast.Constant) \
                or not isinstance(k.value, str):
            resolvable = False      # ** expansion or computed key
            continue
        fields.append(k.value)
        if k.value == "kind" and isinstance(v, ast.Constant) \
                and isinstance(v.value, str):
            kind = v.value
    if kind is None:
        return None
    return kind, (tuple(fields) if resolvable else None)


def _helper_payload(m: ModuleModel, call: ast.Call
                    ) -> Optional[Tuple[str, Optional[Tuple[str, ...]]]]:
    """One-level helper resolution: ``send(_error_payload(...))`` —
    scan the helper's returns for a kind-carrying dict literal."""
    name = _call_name(call)
    fd = m.funcs.get(name) if name else None
    if fd is None:
        return None
    for node in _walk_own(fd.node):
        if isinstance(node, ast.Return) and node.value is not None:
            got = _dict_kind_fields(node.value)
            if got is not None:
                return got
    return None


def _find_sends(m: ModuleModel) -> List[SendSite]:
    out: List[SendSite] = []
    for fd in sorted(set(m.funcs.values()), key=lambda f: f.qualname):
        for node in _walk_own(fd.node):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr != "send" or not node.args:
                continue
            arg = node.args[0]
            got = _dict_kind_fields(arg)
            if got is None and isinstance(arg, ast.Call):
                got = _helper_payload(m, arg)
            if got is None:
                continue
            kind, fields = got
            out.append(SendSite(m.rel, fd.qualname, kind, fields,
                                node.lineno))
    return out


def _is_get_kind(node: ast.AST) -> bool:
    """``<expr>.get("kind")``."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "kind")


def _kind_cmp(node: ast.AST, kindvars: set
              ) -> Optional[Tuple[List[str], bool]]:
    """(compared kinds, is_negative) when ``node`` compares a kind
    expression against constant string(s) — ``==``/``in`` positive,
    ``!=``/``not in`` negative (the rejection-guard shape)."""
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        return None
    left, op, comp = node.left, node.ops[0], node.comparators[0]
    is_kind = (_is_get_kind(left)
               or (isinstance(left, ast.Name) and left.id in kindvars))
    if not is_kind:
        return None
    if isinstance(op, (ast.Eq, ast.NotEq)):
        if isinstance(comp, ast.Constant) and isinstance(comp.value,
                                                         str):
            return [comp.value], isinstance(op, ast.NotEq)
        return None
    if isinstance(op, (ast.In, ast.NotIn)) \
            and isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
        kinds = [el.value for el in comp.elts
                 if isinstance(el, ast.Constant)
                 and isinstance(el.value, str)]
        if kinds:
            return kinds, isinstance(op, ast.NotIn)
    return None


def _chain_has_else(node: ast.If, kindvars: set) -> bool:
    """True when an ``== kind`` if/elif chain bottoms out in a
    non-empty else — the chain-shaped unknown-kind rejection."""
    while True:
        if not node.orelse:
            return False
        if len(node.orelse) == 1 and isinstance(node.orelse[0], ast.If):
            nxt = node.orelse[0]
            if _kind_cmp(nxt.test, kindvars) is not None:
                node = nxt
                continue
        return True     # non-chain else body: the rejection branch


def _find_handles(m: ModuleModel
                  ) -> Tuple[List[HandleSite], List[Dispatcher]]:
    handles: List[HandleSite] = []
    dispatchers: List[Dispatcher] = []
    for fd in sorted(set(m.funcs.values()), key=lambda f: f.qualname):
        kindvars = {t.id for n in _walk_own(fd.node)
                    if isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and _is_get_kind(n.value)
                    for t in n.targets}
        fn_handles: List[HandleSite] = []
        rejects = False
        for node in _walk_own(fd.node):
            got = _kind_cmp(node, kindvars) if isinstance(
                node, ast.Compare) else None
            if got is not None:
                for kind in got[0]:
                    fn_handles.append(HandleSite(m.rel, fd.qualname,
                                                 kind, node.lineno))
            if isinstance(node, ast.If):
                test = _kind_cmp(node.test, kindvars)
                if test is None:
                    continue
                if test[1]:
                    rejects = True       # != / not-in guard
                elif _chain_has_else(node, kindvars):
                    rejects = True       # ==-chain with final else
        if fn_handles:
            handles.extend(fn_handles)
            dispatchers.append(Dispatcher(
                m.rel, fd.qualname,
                min(h.line for h in fn_handles), rejects))
    return handles, dispatchers


@dataclass
class ChannelExtract:
    spec: Dict[str, Any]
    sends: Optional[List[SendSite]]        # None: sender not in tree
    handles: Optional[List[HandleSite]]    # None: receiver not in tree
    dispatchers: Optional[List[Dispatcher]]


def extract_channels(tm: TreeModel) -> List[ChannelExtract]:
    """The observed wire protocol, one entry per declared channel.
    Channels whose modules are absent from the tree (synthetic test
    fixtures) extract as None and are skipped by the rules — the
    checks are spec-path-bound."""
    out: List[ChannelExtract] = []
    for chan in specs.WIRE_CHANNELS:
        smod = tm.modules.get(chan["sender"])
        rmod = tm.modules.get(chan["receiver"])
        sends = _find_sends(smod) if smod is not None else None
        handles, disp = (_find_handles(rmod) if rmod is not None
                         else (None, None))
        out.append(ChannelExtract(chan, sends, handles, disp))
    return out


# ---------------------------------------------------------------- rules

def check_wire_vocabulary(tm: TreeModel,
                          reports: List[ModelReport]) -> List[Finding]:
    findings: List[Finding] = []
    for ce in extract_channels(tm):
        chan = ce.spec
        name, kinds = chan["name"], chan["kinds"]
        if ce.sends is not None and ce.handles is not None:
            handled = {h.kind for h in ce.handles}
            sent = {s.kind for s in ce.sends}
            flagged: set = set()
            for s in ce.sends:
                if s.kind in handled or s.kind in flagged:
                    continue
                flagged.add(s.kind)
                findings.append(Finding(
                    "wire-vocabulary", chan["sender"],
                    f"kind '{s.kind}' is sent on {name} (in "
                    f"{s.func}) but {chan['receiver']} has no "
                    f"branch for it — the receiver would drop it "
                    f"as noise or misread it entirely",
                    line=s.line,
                    key=f"sent-unhandled|{name}|{s.kind}"))
            for kind in sorted(handled - sent):
                spec = kinds.get(kind)
                if spec is not None and spec.get("sent") is False:
                    continue        # sanctioned (spec carries a note)
                h = next(x for x in ce.handles if x.kind == kind)
                findings.append(Finding(
                    "wire-vocabulary", chan["receiver"],
                    f"kind '{kind}' is handled on {name} (in "
                    f"{h.func}) but {chan['sender']} never sends "
                    f"it — dead vocabulary (declare it sent: False "
                    f"in protocol_specs with a note, or delete the "
                    f"branch)",
                    line=h.line,
                    key=f"handled-unsent|{name}|{kind}"))
        if ce.dispatchers is not None:
            for d in ce.dispatchers:
                if d.rejects:
                    continue
                findings.append(Finding(
                    "wire-vocabulary", chan["receiver"],
                    f"{d.func} dispatches on msg kinds from {name} "
                    f"with no explicit unknown-kind rejection: a "
                    f"typo'd or future kind silently falls through "
                    f"— add a != guard or a final else that "
                    f"rejects/logs it",
                    line=d.line,
                    key=f"no-unknown-rejection|{name}|{d.func}"))
    return findings


def check_wire_field_contract(tm: TreeModel,
                              reports: List[ModelReport]
                              ) -> List[Finding]:
    findings: List[Finding] = []
    for ce in extract_channels(tm):
        if ce.sends is None:
            continue
        chan = ce.spec
        name = chan["name"]
        for s in ce.sends:
            spec = chan["kinds"].get(s.kind)
            if spec is None or s.fields is None:
                continue    # undeclared kind → drift rule's job
            allowed = set(spec["required"]) | set(spec.get("optional",
                                                           ()))
            for fld in spec["required"]:
                if fld not in s.fields:
                    findings.append(Finding(
                        "wire-field-contract", s.module,
                        f"send of kind '{s.kind}' in {s.func} omits "
                        f"required field '{fld}' ({name} contract)",
                        line=s.line,
                        key=f"missing|{name}|{s.kind}|{fld}"))
            for fld in s.fields:
                if fld not in allowed:
                    findings.append(Finding(
                        "wire-field-contract", s.module,
                        f"send of kind '{s.kind}' in {s.func} "
                        f"carries undeclared field '{fld}' — extend "
                        f"the {name} spec row first",
                        line=s.line,
                        key=f"undeclared|{name}|{s.kind}|{fld}"))
    return findings


def check_spec_drift(tm: TreeModel,
                     reports: List[ModelReport]) -> List[Finding]:
    findings: List[Finding] = []
    for ce in extract_channels(tm):
        chan = ce.spec
        name, kinds = chan["name"], chan["kinds"]
        if ce.sends is not None:
            sent = {s.kind for s in ce.sends}
            for kind, spec in sorted(kinds.items()):
                if spec.get("sent", True) and kind not in sent:
                    findings.append(Finding(
                        "protocol-spec-drift", chan["sender"],
                        f"spec declares kind '{kind}' sent on "
                        f"{name} but no send site exists — the "
                        f"spec row is stale (or the sender "
                        f"regressed)",
                        key=f"unsent|{name}|{kind}"))
                if spec.get("sent") is False and kind in sent:
                    s = next(x for x in ce.sends if x.kind == kind)
                    findings.append(Finding(
                        "protocol-spec-drift", chan["sender"],
                        f"spec declares kind '{kind}' as never-sent "
                        f"on {name} ({spec.get('note', 'no note')}) "
                        f"but {s.func} sends it",
                        line=s.line,
                        key=f"sent-despite-spec|{name}|{kind}"))
            for s in ce.sends:
                if s.kind not in kinds:
                    findings.append(Finding(
                        "protocol-spec-drift", chan["sender"],
                        f"kind '{s.kind}' (sent in {s.func}) is not "
                        f"declared in the {name} spec — add its row "
                        f"to protocol_specs.WIRE_CHANNELS first",
                        line=s.line,
                        key=f"undeclared-kind|{name}|{s.kind}"))
        if ce.handles is not None:
            handled = {h.kind for h in ce.handles}
            for kind in sorted(kinds):
                if kind not in handled:
                    findings.append(Finding(
                        "protocol-spec-drift", chan["receiver"],
                        f"spec declares kind '{kind}' on {name} but "
                        f"{chan['receiver']} has no handler branch "
                        f"for it",
                        key=f"unhandled|{name}|{kind}"))
            for kind in sorted(handled - set(kinds)):
                h = next(x for x in ce.handles if x.kind == kind)
                findings.append(Finding(
                    "protocol-spec-drift", chan["receiver"],
                    f"kind '{kind}' (handled in {h.func}) is not "
                    f"declared in the {name} spec",
                    line=h.line,
                    key=f"undeclared-kind|{name}|{kind}"))
    # declared transition sites must still exist
    for sites_table in (specs.LIFECYCLE_SITES, specs.COMMIT_SITES):
        for rel, quals in sites_table.items():
            m = tm.modules.get(rel)
            if m is None:
                continue
            for qual in quals:
                if qual not in m.funcs:
                    findings.append(Finding(
                        "protocol-spec-drift", rel,
                        f"declared protocol transition site {qual} "
                        f"no longer exists in {rel} — a rename/"
                        f"removal must edit the spec table too",
                        key=f"missing-site|{rel}|{qual}"))
    # the model checker's invariant sets must match the declared table
    actual = {r.name: tuple(r.invariants) for r in reports}
    for model in sorted(set(specs.MODEL_INVARIANTS) | set(actual)):
        want = specs.MODEL_INVARIANTS.get(model)
        got = actual.get(model)
        if want != got:
            findings.append(Finding(
                "protocol-spec-drift", f"model:{model}",
                f"invariant drift for model '{model}': spec "
                f"declares {list(want) if want else None}, checker "
                f"implements {list(got) if got else None}",
                key=f"invariant-drift|{model}"))
    return findings


def check_modelcheck(tm: TreeModel,
                     reports: List[ModelReport]) -> List[Finding]:
    findings: List[Finding] = []
    for r in reports:
        if not r.complete:
            findings.append(Finding(
                "modelcheck-invariant", f"model:{r.name}",
                f"model '{r.name}' exhausted its state budget "
                f"({r.states} states explored) — an unexplorable "
                f"model proves nothing; shrink the model or raise "
                f"STATE_BUDGET deliberately",
                key=f"{r.name}|budget"))
        for v in r.violations:
            sched = " -> ".join(v["trace"]) or "<initial state>"
            findings.append(Finding(
                "modelcheck-invariant", f"model:{r.name}",
                f"invariant '{v['invariant']}' violated in model "
                f"'{r.name}': {v['msg']} [schedule: {sched}]",
                key=f"{r.name}|{v['invariant']}",
                detail={"trace": v["trace"]}))
    return findings


def check_commit_order(tm: TreeModel,
                       reports: List[ModelReport]) -> List[Finding]:
    """The v3 two-phase-commit ORDER (migrated from concurrency_lint,
    PR 15 → 18): within any function that both renames artifact files
    into place (``os.replace``) and publishes a checkpoint manifest,
    every publish must come AFTER the last rename — a manifest
    published before a shard rename points at files that may never
    land, exactly the torn read the commit protocol rules out."""
    findings: List[Finding] = []
    for rel in sorted(tm.modules):
        m = tm.modules[rel]
        for fd in sorted(set(m.funcs.values()),
                         key=lambda f: f.qualname):
            commits: List[int] = []
            replaces: List[int] = []
            for node in _walk_own(fd.node):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name in specs.MANIFEST_COMMITTERS:
                    commits.append(node.lineno)
                elif name == "replace" and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "os":
                    replaces.append(node.lineno)
            if not commits or not replaces:
                continue
            first_commit = min(commits)
            late = [ln for ln in replaces if ln > first_commit]
            if late:
                findings.append(Finding(
                    "ckpt-commit-order", m.rel,
                    f"{fd.qualname} publishes the checkpoint "
                    f"manifest (line {first_commit}) BEFORE a shard "
                    f"rename (line {late[0]}): the commit record "
                    f"would point at files that may never land — "
                    f"publish the manifest only after every shard's "
                    f"os.replace",
                    line=first_commit,
                    key=f"commit-order|{fd.qualname}"))
    return findings


_CHECKS = {
    "wire-vocabulary": check_wire_vocabulary,
    "wire-field-contract": check_wire_field_contract,
    "protocol-spec-drift": check_spec_drift,
    "modelcheck-invariant": check_modelcheck,
    "ckpt-commit-order": check_commit_order,
}


# -------------------------------------------------- surface + entrypoint

def protocol_surface(tm: TreeModel,
                     reports: List[ModelReport]) -> Dict[str, Any]:
    """The extracted protocol, machine-readable: per-channel kind
    tables (spec contract + observed send/handle sites), the
    lifecycle/commit transition-site index, the checkpoint artifact
    inventory (the one PR-15 migrated here), and each model's
    exploration verdict — the payload behind ``--json``'s
    ``protocol_surface`` and ``python -m roc_tpu.report
    --protocol``."""
    channels: List[Dict[str, Any]] = []
    for ce in extract_channels(tm):
        chan = ce.spec
        sends = ce.sends or []
        handles = ce.handles or []
        kinds: Dict[str, Any] = {}
        for kind in sorted(set(chan["kinds"])
                           | {s.kind for s in sends}
                           | {h.kind for h in handles}):
            spec = chan["kinds"].get(kind)
            sent_at = sorted(s.line for s in sends if s.kind == kind)
            handled_at = sorted(h.line for h in handles
                                if h.kind == kind)
            if spec is None:
                status = "undeclared"
            elif (sent_at or spec.get("sent") is False) \
                    and handled_at:
                status = "ok"
            else:
                status = "drift"
            kinds[kind] = {
                "required": list(spec["required"]) if spec else None,
                "optional": list(spec.get("optional", ()))
                if spec else None,
                "sent": spec.get("sent", True) if spec else None,
                "note": spec.get("note") if spec else None,
                "sent_at": sent_at, "handled_at": handled_at,
                "status": status}
        channels.append({
            "name": chan["name"], "sender": chan["sender"],
            "receiver": chan["receiver"], "kinds": kinds,
            "dispatchers": [{"func": d.func, "line": d.line,
                             "rejects_unknown": d.rejects}
                            for d in (ce.dispatchers or [])]})
    sites: List[Dict[str, Any]] = []
    for machine, table in (("lifecycle", specs.LIFECYCLE_SITES),
                           ("commit", specs.COMMIT_SITES)):
        for rel in sorted(table):
            m = tm.modules.get(rel)
            if m is None:
                continue
            for qual in table[rel]:
                fd = m.funcs.get(qual)
                sites.append({
                    "machine": machine, "module": rel, "site": qual,
                    "line": fd.node.lineno if fd else None,
                    "present": fd is not None})
    artifacts: List[Dict[str, Any]] = []
    for rel in sorted(tm.modules):
        arts = specs.ckpt_artifact_entries(tm.modules[rel].tree)
        if arts:
            artifacts.append({"module": rel, "artifacts": arts})
    models = [r.to_json() for r in reports]
    return {
        "channels": channels,
        "sites": sites,
        "artifacts": artifacts,
        "models": models,
        "state_budget": STATE_BUDGET,
        "totals": {
            "channels": len(channels),
            "kinds": sum(len(c["kinds"]) for c in channels),
            "send_sites": sum(len(k["sent_at"])
                              for c in channels
                              for k in c["kinds"].values()),
            "sites": len(sites),
            "artifacts": sum(len(a["artifacts"]) for a in artifacts),
            "models": len(models),
            "states": sum(m["states"] for m in models),
            "transitions": sum(m["transitions"] for m in models),
            "violations": sum(len(m["violations"]) for m in models),
        }}


def run_protocol_lint(root: str,
                      select: Optional[List[str]] = None,
                      tree_model: Optional[TreeModel] = None,
                      model_reports: Optional[List[ModelReport]] = None
                      ) -> List[Finding]:
    """Run the selected (default: all) protocol rules over ``root``.
    Pure AST + bounded BFS — no jax, milliseconds.  Per-line pragma
    suppression applies to module-located findings; model-located
    findings (``model:*`` units) have no source line to waive."""
    tm = tree_model if tree_model is not None else TreeModel(root)
    need_models = select is None or any(
        s in ("modelcheck-invariant", "protocol-spec-drift")
        for s in select)
    reports = (model_reports if model_reports is not None
               else (check_all() if need_models else []))
    findings: List[Finding] = []
    for name, check in _CHECKS.items():
        if select is not None and name not in select:
            continue
        for f in check(tm, reports):
            m = tm.modules.get(f.unit)
            if m is not None and pragma_ok(m.lines, f.line, f.rule):
                continue
            findings.append(f)
    return findings


def audit_protocol(root: str,
                   select: Optional[List[str]] = None,
                   extras: Optional[Dict[str, Any]] = None
                   ) -> List[Finding]:
    """Level-eight entry point for the driver: run the rules (one
    shared model-checking pass), stash the surface under
    ``extras['protocol']``, and emit it as a ``protocol`` event
    (kind=``protocol_surface``) so a run artifact documents its own
    wire vocabulary and ``python -m roc_tpu.report --protocol`` can
    render the tables from the event stream alone."""
    from ..obs.events import emit
    tm = TreeModel(root)
    reports = check_all()
    findings = run_protocol_lint(root, select=select, tree_model=tm,
                                 model_reports=reports)
    surface = protocol_surface(tm, reports)
    if extras is not None:
        extras["protocol"] = surface
    t = surface["totals"]
    emit("protocol",
         f"protocol surface: {t['kinds']} wire kind(s) on "
         f"{t['channels']} channel(s), {t['sites']} transition "
         f"site(s), {t['models']} model(s) / {t['states']} state(s) "
         f"explored, {t['violations']} violation(s)",
         console=False, kind="protocol_surface",
         channels=surface["channels"], models=surface["models"],
         totals=t)
    return findings
