"""Assemble lint units and run every rule — the engine behind
``python -m roc_tpu.analysis``.

The trace stage builds BOTH trainers against a small synthetic
dataset (the same 8-virtual-device CPU rig the test tier uses), traces
their train/eval step functions and the recorded-op model graph to
ClosedJaxprs, and compiles the single-device train step once for the
HLO rules.  Mixed precision (fp32 master / bf16 compute) is used so
the bf16-path rules actually arm — the invariants under lint are the
production configs', not float32 toy semantics.

Findings are emitted as ``analysis``-category obs events (JSONL
artifact + machine-readable CI trail) in addition to being returned.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..obs.events import emit
from .ast_lint import RULES as AST_RULES, run_ast_lint
from .collective_lint import (COLLECTIVE_RULES, CollectiveUnit,
                              check_ring_halo, run_collective_lint)
from .concurrency_lint import CONCURRENCY_RULES, audit_concurrency
from .findings import Finding, dedupe
from .hlo_lint import check_bytes_model, check_large_copy
from .jaxpr_lint import JAXPR_RULES, JaxprUnit, run_jaxpr_lint
from .programspace import (_C, _DEG, _F, _H, _V, PROGRAMSPACE_RULES,
                           audit_program_space)
from .protocol_lint import PROTOCOL_RULES, audit_protocol
from .sharding_lint import SHARDING_RULES, audit_sharding

HLO_RULES = ("hlo-large-copy", "hlo-bytes-model")

# trace-stage rules that are neither jaxpr- nor hlo- prefixed: they
# inspect the BUILT trainers (here: the distributed trainer's actual
# partition / ring tables), so they need the same 8-virtual-device rig
EXTRA_TRACE_RULES = ("partition-imbalance", "collective-ring-halo")

# a recorded max/mean edge imbalance past this on >1 device means the
# slowest shard gates every SPMD step by >= 50% over the mean — the
# split (or the vertex order feeding it) needs attention
IMBALANCE_THRESHOLD = 1.5


def is_trace_rule(name: str) -> bool:
    """True for rules that need the jax trace/build stage (jaxpr-*,
    hlo-*, collective-*, the program-space and sharding auditors, and
    the built-trainer checks) — shared by the driver's stage gating
    and the CLI's stale-entry scoping."""
    return (name.startswith(("jaxpr-", "hlo-", "collective-"))
            or name in EXTRA_TRACE_RULES
            or name in PROGRAMSPACE_RULES
            or name in SHARDING_RULES)


def check_partition_imbalance(unit: str, real_edges,
                              num_parts: Optional[int] = None,
                              threshold: float = IMBALANCE_THRESHOLD
                              ) -> List[Finding]:
    """[partition-imbalance] warn when the recorded ``max/mean`` edge
    imbalance of a >1-device partition exceeds ``threshold`` — the
    straggler shard would gate every step and every ring hop.  Fed by
    the per-part real edge counts the trainer records in its manifest
    (``partition_static_stats``); baselined through the shrink-only
    ratchet like every other rule."""
    import numpy as np
    real_edges = np.asarray(real_edges, dtype=np.float64)
    if num_parts is None:
        num_parts = int(real_edges.shape[0])
    if num_parts < 2 or real_edges.size == 0:
        return []
    mean = float(real_edges.sum()) / num_parts
    if mean <= 0:
        return []
    ratio = float(real_edges.max()) / mean
    if ratio <= threshold:
        return []
    return [Finding(
        "partition-imbalance", unit,
        f"edge imbalance max/mean {ratio:.2f} > {threshold} across "
        f"{num_parts} devices — the slowest shard gates every SPMD "
        f"step (use --partition cost / --rebalance, or reorder the "
        f"vertex ids)",
        key=f"parts={num_parts}",
        detail={"ratio": round(ratio, 4), "threshold": threshold})]

# synthetic rig: big enough that activation scale ([V, F]) dominates
# class-width tensors ([V, C]) AND per-device activation scale
# (V/8 * F on the mesh) dominates parameter scale (F * H) by the
# margins the rules assume; small enough that the whole stage
# (3 trainer builds + 1 CPU compile) stays inside the tier's <60 s
# budget.  The scale constants (_V/_DEG/_F/_C/_H) are defined ONCE in
# programspace and imported at the top of this module (the reverse
# import would cycle), so the jaxpr-lint stage and the program-space
# auditor can never check different synthetic rigs.


def all_rule_names() -> List[str]:
    return ([r.name for r in AST_RULES] + list(CONCURRENCY_RULES)
            + list(PROTOCOL_RULES) + list(JAXPR_RULES)
            + list(HLO_RULES) + list(EXTRA_TRACE_RULES)
            + list(COLLECTIVE_RULES) + list(PROGRAMSPACE_RULES)
            + list(SHARDING_RULES))


def _needs_trace(select: Optional[List[str]]) -> bool:
    """True when the jaxpr/HLO/collective trainer-build stage must
    run.  Program-space and sharding rules have their own rig builds
    (audit_program_space / audit_sharding) and alone don't need this
    stage."""
    if select is None:
        return True
    return any(is_trace_rule(s) and s not in PROGRAMSPACE_RULES
               and s not in SHARDING_RULES
               for s in select)


def build_trace_findings(select: Optional[List[str]] = None,
                         hlo_factor: float = 32.0) -> List[Finding]:
    """Trace/compile the step functions and run the jaxpr + HLO rules.
    Needs a jax backend (the CLI forces the 8-virtual-device CPU rig);
    import stays inside so the AST-only path never touches jax."""
    import jax
    import jax.numpy as jnp

    from ..core.graph import synthetic_dataset
    from ..models.gcn import build_gcn
    from ..train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(num_nodes=_V, avg_degree=_DEG, in_dim=_F,
                           num_classes=_C, seed=0)
    cfg = TrainConfig(verbose=False, symmetric=True,
                      dtype=jnp.float32, compute_dtype=jnp.bfloat16)
    model = build_gcn([_F, _H, _C], dropout_rate=0.5)
    tr = Trainer(model, ds, cfg)
    key = jax.random.PRNGKey(0)
    lr = jnp.asarray(0.01, jnp.float32)
    donate_min = max(int(v.size) * v.dtype.itemsize
                     for v in jax.tree_util.tree_leaves(tr.params))
    ctx: Dict[str, Any] = dict(
        compute_dtype="bfloat16", num_nodes=_V, vf_elems=_V * _F,
        halo="gather", donate_min_bytes=donate_min)

    units = [
        JaxprUnit("train_step", jax.make_jaxpr(tr._train_step._jit)(
            tr.params, tr.opt_state, key, lr, tr.feats, tr.labels,
            tr.mask, tr.gctx), **ctx),
        JaxprUnit("eval_step", jax.make_jaxpr(tr._eval_step._jit)(
            tr.params, tr.feats, tr.labels, tr.mask, tr.gctx), **ctx),
        # the recorded-op model graph, traced directly (no pjit): the
        # builder's interpreter is where an op-list rewrite (fusion,
        # streaming split) would first leak an anti-pattern
        JaxprUnit("model_graph", jax.make_jaxpr(
            lambda p: tr.model.loss_fn(
                p, tr.feats, tr.labels, tr.mask, tr.gctx, key=key,
                train=True))(tr.params), **ctx),
    ]

    # the host-feature streaming tier: its device-resident steps
    # (tail grad + the optimizer apply) are separate dispatch
    # boundaries with their own donation contracts
    str_tr = Trainer(build_gcn([_F, _H, _C], dropout_rate=0.5), ds,
                     TrainConfig(verbose=False, symmetric=True,
                                 features="host",
                                 dtype=jnp.float32,
                                 compute_dtype=jnp.bfloat16))
    y = jnp.zeros((_V, _H), jnp.bfloat16)
    grads = jax.tree_util.tree_map(jnp.zeros_like, str_tr.params)
    units.append(JaxprUnit(
        "tail_grad", jax.make_jaxpr(str_tr._tail_grad._jit)(
            str_tr.params, y, key, str_tr.labels, str_tr.mask,
            str_tr.gctx), **ctx))
    units.append(JaxprUnit(
        "apply_update", jax.make_jaxpr(str_tr._apply_update._jit)(
            str_tr.params, str_tr.opt_state, grads, lr), **ctx))

    if len(jax.devices()) > 1:
        from ..parallel.distributed import DistributedTrainer
        parts = len(jax.devices())
        dtr = DistributedTrainer(
            build_gcn([_F, _H, _C], dropout_rate=0.5), ds, parts,
            TrainConfig(verbose=False, symmetric=True,
                        dtype=jnp.float32,
                        compute_dtype=jnp.bfloat16))
        d = dtr.data
        fuse_tabs = (d.ell_w, d.sect_w, d.ring_w, d.bd_scale)
        dctx = dict(ctx)
        dctx["halo"] = dtr.config.halo
        # shard_map body avals are block-local: scale-relative rules
        # compare against the PER-DEVICE activation footprint
        dctx["vf_elems"] = (_V * _F) // parts
        dctx["mesh_parts"] = parts
        units.append(JaxprUnit(
            "dist_train_step", jax.make_jaxpr(dtr._train_step._jit)(
                dtr.params, dtr.opt_state, d.feats, d.labels, d.mask,
                d.edge_src, d.edge_dst, d.in_degree, d.ell_idx,
                d.ell_row_pos, d.ell_row_id, d.ring_idx, d.sect_idx,
                d.sect_sub_dst, d.bd_tabs, fuse_tabs, key, lr),
            **dctx))
        units.append(JaxprUnit(
            "dist_eval_step", jax.make_jaxpr(dtr._eval_step._jit)(
                dtr.params, d.feats, d.labels, d.mask, d.edge_src,
                d.edge_dst, d.in_degree, d.ell_idx, d.ell_row_pos,
                d.ell_row_id, d.ring_idx, d.sect_idx, d.sect_sub_dst,
                d.bd_tabs, fuse_tabs),
            **dctx))

    findings = run_jaxpr_lint(units, select=select)

    if len(jax.devices()) > 1 and (select is None
                                   or "partition-imbalance" in select):
        # the split the distributed trainer ACTUALLY built on the rig
        findings.extend(check_partition_imbalance(
            "partition:dist_trainer", dtr.pg.real_edges,
            dtr.pg.num_parts))

    collective_selected = (select is None or any(
        s.startswith("collective-") for s in select))
    if len(jax.devices()) > 1 and collective_selected:
        from jax.sharding import PartitionSpec as P

        from ..parallel.distributed import PARTS_AXIS, _shard_map
        from ..parallel.ring import build_ring_tables, ring_aggregate
        axes = {PARTS_AXIS: parts}
        # the dist steps' traced collectives (gradient psum, halo
        # gather, metrics reduction) re-use the jaxprs above
        by_name = {u.name: u for u in units}
        cunits = [CollectiveUnit(n, by_name[n].jaxpr, axes)
                  for n in ("dist_train_step", "dist_eval_step")
                  if n in by_name]
        # the ring-halo subroutine, traced standalone: the gather-halo
        # trainer above never emits a ppermute, and the ring schedule
        # is exactly what the cycle rule exists to verify
        rt = build_ring_tables(dtr.pg)
        ring_fn = _shard_map(
            lambda x, s_, d_: ring_aggregate(
                x[0], s_[0], d_[0], axis_name=PARTS_AXIS),
            dtr.mesh, (P(PARTS_AXIS),) * 3, P(PARTS_AXIS))
        cunits.append(CollectiveUnit(
            "ring_halo", jax.make_jaxpr(ring_fn)(
                jnp.zeros((parts, dtr.pg.part_nodes, 8), jnp.float32),
                jnp.asarray(rt.src), jnp.asarray(rt.dst)), axes))
        findings.extend(run_collective_lint(cunits, select=select))
        if select is None or "collective-ring-halo" in select:
            # structural: the ring tables vs the plan's halo stats —
            # two independent derivations of the same exchange
            findings.extend(check_ring_halo(
                "collective:ring_tables", dtr.pg, rt))

    hlo_selected = (select is None
                    or any(s.startswith("hlo-") for s in select))
    if hlo_selected:
        from ..obs.compile_watch import cost_summary
        compiled = tr._train_step._jit.lower(
            tr.params, tr.opt_state, key, lr, tr.feats, tr.labels,
            tr.mask, tr.gctx).compile()
        if select is None or "hlo-large-copy" in select:
            findings.extend(check_large_copy(
                "hlo:train_step", compiled.as_text(),
                copy_min_elems=_V * _F))
        if select is None or "hlo-bytes-model" in select:
            findings.extend(check_bytes_model(
                "hlo:train_step",
                cost_summary(compiled).get("bytes_accessed"),
                tr._modeled_bytes, factor=hlo_factor))
    return findings


def _needs_programspace(select: Optional[List[str]]) -> bool:
    if select is None:
        return True
    return any(s in PROGRAMSPACE_RULES for s in select)


def _needs_sharding(select: Optional[List[str]]) -> bool:
    if select is None:
        return True
    return any(s in SHARDING_RULES for s in select)


def analyze(root: str, select: Optional[List[str]] = None,
            trace: bool = True,
            program_budget: Optional[Dict[str, int]] = None,
            replication_budget: Optional[Dict[str, int]] = None,
            extras: Optional[Dict[str, Any]] = None) -> List[Finding]:
    """AST lint over ``root`` plus (when ``trace`` and a trace rule is
    selected) the jaxpr/HLO/collective stage and the program-space
    and sharding auditors.  Every finding is also emitted as an
    ``analysis``-category event.

    ``program_budget`` / ``replication_budget`` are the ratcheted
    per-rig-config bounds for the compile-explosion and
    replication-budget rules; None loads them from ``root``'s
    ``scripts/lint_baseline.json``.  ``extras``, when a dict,
    receives the auditors' reports under ``'programspace'`` /
    ``'sharding'``."""
    t0 = time.perf_counter()
    baseline_path = None
    if program_budget is None or replication_budget is None:
        import os
        baseline_path = os.path.join(root, "scripts",
                                     "lint_baseline.json")
    findings = run_ast_lint(root, select=select)
    # level six: the concurrency/signal-safety auditor — pure AST
    # (no jax, no trace stage), so it runs under every selection that
    # names one of its rules, including `--select concurrency`
    if select is None or any(s in CONCURRENCY_RULES for s in select):
        findings.extend(audit_concurrency(root, select=select,
                                          extras=extras))
    # level eight: the protocol auditor & bounded model checker —
    # pure AST + pure-Python BFS, same millisecond class as level six
    if select is None or any(s in PROTOCOL_RULES for s in select):
        findings.extend(audit_protocol(root, select=select,
                                       extras=extras))
    if trace and _needs_trace(select):
        findings.extend(build_trace_findings(select=select))
    if trace and _needs_programspace(select):
        if program_budget is None:
            from .findings import load_program_budget
            program_budget = load_program_budget(baseline_path)
        findings.extend(audit_program_space(
            select=select, program_budget=program_budget,
            extras=extras))
    # level seven: the sharding & replication auditor — its own rig
    # builds (no compiles), like the program-space level
    if trace and _needs_sharding(select):
        if replication_budget is None:
            from .findings import load_budget
            replication_budget = load_budget(baseline_path,
                                             "replication_budget")
        findings.extend(audit_sharding(
            select=select, replication_budget=replication_budget,
            extras=extras))
    findings = dedupe(findings)
    for f in findings:
        emit("analysis", f.render(), console=False, rule=f.rule,
             unit=f.unit, line=f.line, fingerprint=f.fingerprint)
    emit("analysis",
         f"roc-lint: {len(findings)} finding(s) in "
         f"{time.perf_counter() - t0:.1f}s", console=False,
         count=len(findings),
         rules=sorted({f.rule for f in findings}))
    return findings
