"""roc-lint: trace-level static analysis for jaxpr/HLO anti-patterns
plus a rule-driven source lint — regressions against the invariants the
ROC performance story rests on are caught BEFORE merge, not after a
chip run.

Eight levels, mirroring XLA's own cost_analysis / HLO-verifier split:

- :mod:`ast_lint` — source-level rules over the tree (stdout
  discipline, host syncs in hot paths, jits bypassing the compile
  observer, pallas interpret plumbing);
- :mod:`concurrency_lint` — the host-side threading/signal surface
  (lock-order cycles, signal-handler safety, condvar predicates,
  unguarded shared state, blocking under locks, thread shutdown
  paths, multi-process artifact-lock ownership), jax-free like the
  AST level;
- :mod:`jaxpr_lint` — rules over the ClosedJaxprs of both trainers'
  step functions and the recorded-op model graph (bf16 upcasts,
  host callbacks under jit, large non-donated buffers, cross-shard
  materialization, int32 index-overflow hazards);
- :mod:`hlo_lint` — rules over the optimized HLO text +
  ``cost_analysis`` that ``ObservedJit`` already captures
  (fusion-breaking copies of activation-scale tensors, bytes-accessed
  vs the core/memory.py model);
- :mod:`programspace` — the enumerated compiled-program set and its
  shrink-only ``program_budget`` ratchet;
- :mod:`collective_lint` — SPMD collective choreography at P>=2;
- :mod:`sharding_lint` — sharding propagation over the candidate
  jaxprs: the replication ledger vs ``replication_budget``,
  full-width re-gathers, sharding mismatches, donation under
  sharding, and the (parts, model) mesh-portability report;
- :mod:`protocol_lint` — the protocol auditor & bounded model
  checker: AST-extracted wire vocabulary of the router<->replica
  channels held against :mod:`protocol_specs`'s declared contracts
  (per-kind field sets, unknown-kind rejection), plus
  :mod:`modelcheck`'s exhaustive bounded BFS over crash/interleave
  schedules of the router request lifecycle, the checkpoint v3
  two-phase commit, and the versioned-table swap — jax-free like
  the AST and concurrency levels.

:mod:`driver` assembles the lint units (synthetic dataset, both
trainers, the 8-virtual-device mesh) and runs every rule;
``python -m roc_tpu.analysis`` is the CLI, ratcheted into tier-1 via
``scripts/lint_baseline.json`` (tests/test_analysis.py).
"""

from .findings import Finding, load_baseline, save_baseline, split_findings


def force_cpu_rig() -> None:
    """Force THE 8-virtual-device CPU rig the analysis levels, the
    prewarm CLI, and the prewarm test workers all audit against.
    jax is typically already imported (roc_tpu/__init__ pulls it in),
    so the JAX_PLATFORMS env var alone would be latched-and-ignored —
    the platform goes through jax.config (like tests/conftest.py);
    XLA_FLAGS is still read at CPU-client init, so the virtual-device
    append works as long as this runs before the first device use.
    ONE implementation: a copy missing the device-count flag is how
    the parts=2 rig got silently skipped-and-never-warmed."""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"   # children / consistency
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


__all__ = ["Finding", "force_cpu_rig", "load_baseline",
           "save_baseline", "split_findings"]
