"""roc-lint: trace-level static analysis for jaxpr/HLO anti-patterns
plus a rule-driven source lint — regressions against the invariants the
ROC performance story rests on are caught BEFORE merge, not after a
chip run.

Three layers, mirroring XLA's own cost_analysis / HLO-verifier split:

- :mod:`ast_lint` — source-level rules over the tree (stdout
  discipline, host syncs in hot paths, jits bypassing the compile
  observer, pallas interpret plumbing);
- :mod:`jaxpr_lint` — rules over the ClosedJaxprs of both trainers'
  step functions and the recorded-op model graph (bf16 upcasts,
  host callbacks under jit, large non-donated buffers, cross-shard
  materialization, int32 index-overflow hazards);
- :mod:`hlo_lint` — rules over the optimized HLO text +
  ``cost_analysis`` that ``ObservedJit`` already captures
  (fusion-breaking copies of activation-scale tensors, bytes-accessed
  vs the core/memory.py model).

:mod:`driver` assembles the lint units (synthetic dataset, both
trainers, the 8-virtual-device mesh) and runs every rule;
``python -m roc_tpu.analysis`` is the CLI, ratcheted into tier-1 via
``scripts/lint_baseline.json`` (tests/test_analysis.py).
"""

from .findings import Finding, load_baseline, save_baseline, split_findings

__all__ = ["Finding", "load_baseline", "save_baseline",
           "split_findings"]
