"""Declared protocol spec tables — roc-lint level eight's contract.

These tables are the DECLARED protocol: the line-JSON wire vocabulary
the router and its replicas speak (per-kind field contracts included),
the request-lifecycle and checkpoint-commit transition sites, and the
invariants the bounded model checker (:mod:`modelcheck`) proves over
the three protocol models.  :mod:`protocol_lint` extracts the ACTUAL
protocol from the AST of the five protocol modules and cross-validates
it against these tables — any disagreement is a ``protocol-spec-drift``
finding, in either direction:

- code sends/handles a kind (or field, or transition site) this file
  does not declare → the change must extend the spec table FIRST;
- this file declares something the code no longer has → the table is
  stale and must shrink.

That makes the spec the extension point for the rollout/autoscaler/
resize PRs: add the new kind's row here, watch the lint tell you every
send/handle/field site the implementation still owes.

This module is jax-free and near-declarative: besides the tables it
carries only the tiny AST helper both the protocol and concurrency
levels use to inventory checkpoint-v3 artifact writers (ONE source of
truth for the callee-name sets — PR 15's inventory migrated here).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List

# ------------------------------------------------------- wire protocol
#
# One entry per directed channel.  Per kind:
#   required  fields every send site of this kind MUST carry
#   optional  fields a send site MAY carry (variant shapes — e.g. the
#             ok/error halves of ``res``)
#   sent      False for kinds the in-tree sender legitimately never
#             puts on the wire (with ``note`` saying why); the
#             wire-vocabulary rule would otherwise flag the receiver
#             branch as dead vocabulary
WIRE_CHANNELS: List[Dict[str, Any]] = [
    {
        "name": "router->replica",
        "sender": "roc_tpu/serve/router.py",
        "receiver": "roc_tpu/serve/replica.py",
        "kinds": {
            "req": {
                "required": ("kind", "id", "ids", "deadline_ms",
                             "rid"),
                "optional": (),
                "sent": True,
            },
            "close": {
                "required": ("kind",),
                "optional": (),
                # Router.close() closes the replica's stdin instead of
                # writing this line: stdin EOF and {"kind": "close"}
                # funnel into the same drain path, and EOF also covers
                # a router that died without draining
                "sent": False,
                "note": "stdin EOF is the close signal "
                        "(Router.close closes the pipe)",
            },
            # sharded-table gather leg (PR 20), declared HERE first
            # per the spec-first workflow: a replica serving a table
            # SLICE fetches rows it does not own from the owning
            # replica, via the router.  The router forwards the
            # requester's fetch_rows to the owner and relays the
            # owner's rows answer back — both kinds therefore exist on
            # BOTH channels.  "version" is the requester's captured
            # TableVersion: the gather is version-PINNED (the owner
            # refuses to answer from a different table version, so a
            # mid-rollout gather can never mix versions — the
            # gather-version-pinned model invariant below).
            "fetch_rows": {
                "required": ("kind", "gid", "ids", "version"),
                "optional": (),
                "sent": True,
            },
            "rows": {
                # ok answers carry the owned rows (raw stored values:
                # fp32 rows, or int8/fp8 codes + per-row scales — the
                # requester stages them verbatim, bit-exact); refusals
                # (version mismatch, un-owned ids) carry "error" with
                # rows empty
                "required": ("kind", "gid", "ids", "rows", "version",
                             "qmode"),
                "optional": ("scales", "replica", "error"),
                "sent": True,
            },
        },
    },
    {
        "name": "replica->router",
        "sender": "roc_tpu/serve/replica.py",
        "receiver": "roc_tpu/serve/router.py",
        "kinds": {
            # the gather leg's other half (PR 20): the REQUESTER
            # replica originates fetch_rows (router forwards it to the
            # owner), and the OWNER replica answers with rows (router
            # relays it back by gid) — same field contracts as the
            # router->replica declarations above, because the router
            # is a pure forwarder that re-builds the line verbatim
            "fetch_rows": {
                "required": ("kind", "gid", "ids", "version"),
                "optional": (),
                "sent": True,
            },
            "rows": {
                "required": ("kind", "gid", "ids", "rows", "version",
                             "qmode"),
                "optional": ("scales", "replica", "error"),
                "sent": True,
            },
            "ready": {
                # "quant" (PR 19): the replica advertises its serving
                # tables' quantization mode (off/int8/fp8) so the
                # router's fleet view can refuse a mixed-mode rollout
                # it did not ask for — declared HERE first, per the
                # spec-first workflow: the wire-field-contract rule
                # then reports every send site still owed the field
                # "table_version" (PR 20): the published TableVersion
                # the replica cold-loaded — the router's fleet view of
                # version skew, and the epoch gathers pin against;
                # "table_bytes" rides along so the capacity scenario
                # can assert the per-replica byte budget from the
                # fleet view (sliced loads advertise O(V/N) bytes)
                "required": ("kind", "replica", "pid", "num_nodes",
                             "num_classes", "buckets", "backend",
                             "shard", "quant", "table_version"),
                "optional": ("table_bytes",),
                "sent": True,
            },
            "hb": {
                "required": ("kind", "inflight", "served", "mono"),
                "optional": (),
                "sent": True,
            },
            "res": {
                "required": ("kind", "id", "ok"),
                # ok=true carries rows+version (+qmode, PR 19: the
                # quant spec of the table VERSION the microbatch was
                # pinned to — a mid-rollout fp32→int8 swap answers
                # with the captured version's mode, and the wire says
                # so); ok=false carries the typed error triple — both
                # shapes are ``res``.  PR 20 adds the answering
                # replica's owned shard range ("shard") and the
                # microbatch's cross-shard gather wall ("gather_ms",
                # None when every id was owned) — the request-path
                # evidence behind the serve_gather_p50_ms column
                "optional": ("rows", "version", "qmode", "error",
                             "msg", "retryable", "shard", "gather_ms"),
                "sent": True,
            },
            "drained": {
                "required": ("kind", "clean", "replica", "served"),
                "optional": (),
                "sent": True,
            },
        },
    },
]

# -------------------------------------------------- transition sites
#
# The request-lifecycle and checkpoint-commit state machines, named by
# the functions that implement their transitions.  Extraction verifies
# each declared site still exists (a rename/removal without a spec
# edit is drift) and the surface reports each site's line — the
# machine-readable "where does this protocol live" index.
LIFECYCLE_SITES: Dict[str, tuple] = {
    # router request lifecycle: admit → dispatch → result/failover/
    # hedge → complete/fail, with the monitor as the deadline backstop
    "roc_tpu/serve/router.py": (
        "Router.submit", "Router._dispatch", "Router._on_result",
        "Router._complete", "Router._fail_sub", "Router._mark_dead",
        "Router._monitor_loop", "Router.close",
    ),
    # replica side: the stdin→drain lifecycle
    "roc_tpu/serve/replica.py": ("serve_loop",),
    # in-process server: admission + the versioned-table microbatch
    "roc_tpu/serve/server.py": (
        "Server.submit", "Server._dispatch", "Server.drain",
        "Server.close",
    ),
}

COMMIT_SITES: Dict[str, tuple] = {
    # checkpoint-v3 two-phase commit: shard writes → renames →
    # manifest publish (the commit record), and the restore-side
    # validators that refuse torn state
    "roc_tpu/utils/checkpoint.py": (
        "write_snapshot", "_write_shard", "commit_manifest",
        "read_manifest", "is_committed",
    ),
    # the async saver drives write_snapshot off the step path;
    # submit/flush are where a stored error re-raises
    "roc_tpu/resilience/async_save.py": (
        "AsyncSaver.submit", "AsyncSaver.flush", "AsyncSaver._process",
    ),
}

# ---------------------------------------------------- model invariants
#
# Declared per-model invariant tables, cross-checked against
# modelcheck.model_invariants() — a model gaining/losing an invariant
# without a spec edit is drift.
MODEL_INVARIANTS: Dict[str, tuple] = {
    "router-lifecycle": (
        "terminal-exactly-once",
        "failover-requeue-at-most-once",
        "no-completion-after-close",
        "deadline-liveness",
    ),
    "ckpt-commit": (
        "manifest-published-last",
        "restore-never-torn",
    ),
    "table-swap": (
        "single-version-batch",
        # PR 19: every published version carries its quant spec, and a
        # row must be DECODED with the qmode of the version it was
        # read from — serving an fp32-captured batch through the int8
        # dequant program (or vice versa, mid-rollout) is garbage even
        # when the version ids agree.  Seedable as "live-qmode".
        "quant-spec-pinned",
        # PR 20: a sharded replica's cross-shard gather must return
        # rows from exactly the version the microbatch captured — a
        # gather answered from the owner's LIVE published version
        # mid-rollout would mix two table versions inside one batch
        # even though every locally-served row is pinned.  Seedable as
        # "shard-gather".
        "gather-version-pinned",
    ),
}

# -------------------------------------- checkpoint artifact inventory
#
# Checkpoint-v3 writer vocabulary (utils/checkpoint.py): the manifest
# publish is the COMMIT RECORD and must follow every shard rename.
# These sets are the ONE source of truth — the protocol level's
# ckpt-commit-order rule and the concurrency level's artifact surface
# both read them (migrated from concurrency_lint, PR 15 → PR 18).
MANIFEST_COMMITTERS = frozenset({"commit_manifest"})
SHARD_WRITERS = frozenset({"write_snapshot", "_write_shard"})


def ckpt_artifact_entries(tree: ast.Module) -> List[Dict[str, Any]]:
    """Checkpoint-v3 artifact inventory for ONE module's AST:
    ``ckpt-shard`` entries for shard-writer call sites (per-process
    ``shard_<proc>.npz`` filenames ARE the ownership evidence) and
    ``ckpt-manifest`` entries for manifest commits (proc-0, after
    every shard rename).  Shared by the protocol surface and the
    concurrency level's artifact surface."""
    out: List[Dict[str, Any]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        callee = (f.id if isinstance(f, ast.Name)
                  else f.attr if isinstance(f, ast.Attribute)
                  else None)
        if callee in SHARD_WRITERS:
            out.append({"kind": "ckpt-shard", "line": node.lineno,
                        "owner": "per-process-file"})
        elif callee in MANIFEST_COMMITTERS:
            out.append({"kind": "ckpt-manifest", "line": node.lineno,
                        "owner": "proc0-commit-after-shards"})
    return out
