"""Finding + baseline ratchet shared by every lint layer.

A finding's *fingerprint* is its identity for baseline matching:
``rule|unit|key`` with a rule-chosen ``key`` that stays stable across
line-number drift and re-runs (shapes and symbols, never line numbers
or wall-clock quantities).  The baseline (``scripts/lint_baseline.json``)
is ratchet-only: :func:`shrink_baseline` can DROP entries that no
longer fire, never add — new findings must be fixed (or suppressed at
the call site with an explanatory ``# roc-lint: ok=<rule>`` pragma),
exactly the lint_prints.sh contract this generalizes.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple


@dataclass
class Finding:
    """One lint hit.  ``unit`` locates the artifact: a repo-relative
    source path for AST rules, ``jaxpr:<step name>`` / ``hlo:<step
    name>`` for trace rules.  ``key`` overrides the fingerprint tail
    (defaults to ``msg`` — rules whose messages embed varying numbers
    must pass a stable key)."""

    rule: str
    unit: str
    msg: str
    line: Optional[int] = None
    key: Optional[str] = None
    detail: Dict[str, Any] = field(default_factory=dict)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.unit}|{self.key or self.msg}"

    def render(self) -> str:
        loc = f"{self.unit}:{self.line}" if self.line else self.unit
        return f"{loc}: [{self.rule}] {self.msg}"


def dedupe(findings: Iterable[Finding]) -> List[Finding]:
    """Drop findings with duplicate fingerprints (e.g. the same upcast
    eqn appearing in forward and recomputed-backward jaxprs) keeping
    first occurrence order."""
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            out.append(f)
    return out


def _load_raw(path: str) -> Dict[str, Any]:
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def load_baseline(path: str) -> Set[str]:
    """Fingerprint set from a baseline file; a missing file is an
    empty baseline (the ratchet starts at zero)."""
    return set(_load_raw(path).get("findings", []))


# ratcheted numeric budget sections of the baseline file.  Each is a
# {config-name: integer bound} map with identical shrink-only
# semantics: a bound can initialize (absent key) and shrink via
# --update-baseline, never grow — growing means fixing the regression
# or hand-editing the JSON (the same deliberate escape hatch as the
# findings list).  ``program_budget`` ratchets the compile-explosion
# program counts (PR 6); ``replication_budget`` ratchets the sharding
# auditor's replicated-bytes-per-step ledger totals (level seven).
BUDGET_SECTIONS = ("program_budget", "replication_budget")


def load_budget(path: str, section: str) -> Dict[str, int]:
    """One ratcheted budget section (``BUDGET_SECTIONS``) from the
    baseline file; missing file/key = no bounds recorded yet."""
    return {str(k): int(v) for k, v in
            _load_raw(path).get(section, {}).items()}


def load_program_budget(path: str) -> Dict[str, int]:
    """Per-rig-config program-count bounds (the compile-explosion
    ratchet) from the same baseline file; missing file/key = no
    bounds recorded yet."""
    return load_budget(path, "program_budget")


def save_baseline(path: str, fingerprints: Iterable[str],
                  program_budget: Optional[Dict[str, int]] = None,
                  budgets: Optional[Dict[str, Dict[str, int]]] = None
                  ) -> None:
    """Write the baseline.  Budget sections not passed are preserved
    from the file untouched — the finding ratchet and each numeric
    ratchet shrink independently.  ``program_budget`` is the legacy
    spelling of ``budgets={'program_budget': ...}``."""
    sections = dict(budgets or {})
    if program_budget is not None:
        sections["program_budget"] = program_budget
    for name in BUDGET_SECTIONS:
        if name not in sections:
            sections[name] = load_budget(path, name)
    data: Dict[str, Any] = {"version": 1,
                            "findings": sorted(set(fingerprints))}
    for name in BUDGET_SECTIONS:
        if sections.get(name):
            data[name] = {k: int(sections[name][k])
                          for k in sorted(sections[name])}
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def shrink_budget(path: str, section: str, counts: Dict[str, int],
                  known: Optional[Set[str]] = None) -> Dict[str, int]:
    """Ratchet-only budget update for one section: for every config
    MEASURED this run, record ``min(stored, measured)`` — a bound can
    initialize (absent key) and shrink, never grow.  Configs not
    measured (e.g. a single-device box skipping the P=2 rig) keep
    their stored bounds.  ``known``, when given, is the full rig
    config-name set: bounds for configs that no longer EXIST (renamed
    or removed rigs — not merely unhosted on this box) are dropped,
    the budget analogue of a stale finding fingerprint.  Returns the
    budget written."""
    budget = load_budget(path, section)
    if known is not None:
        budget = {k: v for k, v in budget.items() if k in known}
    for cfg, n in counts.items():
        budget[cfg] = min(budget.get(cfg, int(n)), int(n))
    save_baseline(path, load_baseline(path), budgets={section: budget})
    return budget


def shrink_program_budget(path: str, counts: Dict[str, int],
                          known: Optional[Set[str]] = None
                          ) -> Dict[str, int]:
    """:func:`shrink_budget` over the compile-explosion section."""
    return shrink_budget(path, "program_budget", counts, known=known)


def _rule_of(fingerprint: str) -> str:
    return fingerprint.split("|", 1)[0]


def split_findings(findings: List[Finding], baseline: Set[str],
                   active_rules: Optional[Set[str]] = None
                   ) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """``(new, baselined, stale)``: findings not covered by the
    baseline, findings the baseline tolerates, and baseline entries
    that no longer fire (candidates for the shrink ratchet).

    ``active_rules`` names the rules that actually RAN: baseline
    entries of rules outside it are never reported stale — a
    ``--select`` run must not declare findings it never looked for
    as gone."""
    new = [f for f in findings if f.fingerprint not in baseline]
    old = [f for f in findings if f.fingerprint in baseline]
    stale = baseline - {f.fingerprint for f in findings}
    if active_rules is not None:
        stale = {fp for fp in stale if _rule_of(fp) in active_rules}
    return new, old, stale


def shrink_baseline(path: str, findings: List[Finding],
                    active_rules: Optional[Set[str]] = None
                    ) -> Set[str]:
    """Ratchet-only update: rewrite ``path`` dropping entries that
    stopped firing — new findings are never absorbed (fix them or
    pragma them; hand-editing the JSON is the deliberate escape
    hatch).  Entries of rules outside ``active_rules`` are kept
    untouched: a selective run only ratchets what it measured.
    Returns the fingerprints written."""
    baseline = load_baseline(path)
    current = {f.fingerprint for f in findings}
    kept = {fp for fp in baseline
            if fp in current
            or (active_rules is not None
                and _rule_of(fp) not in active_rules)}
    save_baseline(path, kept)
    return kept
