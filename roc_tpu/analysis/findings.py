"""Finding + baseline ratchet shared by every lint layer.

A finding's *fingerprint* is its identity for baseline matching:
``rule|unit|key`` with a rule-chosen ``key`` that stays stable across
line-number drift and re-runs (shapes and symbols, never line numbers
or wall-clock quantities).  The baseline (``scripts/lint_baseline.json``)
is ratchet-only: :func:`shrink_baseline` can DROP entries that no
longer fire, never add — new findings must be fixed (or suppressed at
the call site with an explanatory ``# roc-lint: ok=<rule>`` pragma),
exactly the lint_prints.sh contract this generalizes.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple


@dataclass
class Finding:
    """One lint hit.  ``unit`` locates the artifact: a repo-relative
    source path for AST rules, ``jaxpr:<step name>`` / ``hlo:<step
    name>`` for trace rules.  ``key`` overrides the fingerprint tail
    (defaults to ``msg`` — rules whose messages embed varying numbers
    must pass a stable key)."""

    rule: str
    unit: str
    msg: str
    line: Optional[int] = None
    key: Optional[str] = None
    detail: Dict[str, Any] = field(default_factory=dict)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.unit}|{self.key or self.msg}"

    def render(self) -> str:
        loc = f"{self.unit}:{self.line}" if self.line else self.unit
        return f"{loc}: [{self.rule}] {self.msg}"


def dedupe(findings: Iterable[Finding]) -> List[Finding]:
    """Drop findings with duplicate fingerprints (e.g. the same upcast
    eqn appearing in forward and recomputed-backward jaxprs) keeping
    first occurrence order."""
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            out.append(f)
    return out


def _load_raw(path: str) -> Dict[str, Any]:
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def load_baseline(path: str) -> Set[str]:
    """Fingerprint set from a baseline file; a missing file is an
    empty baseline (the ratchet starts at zero)."""
    return set(_load_raw(path).get("findings", []))


def load_program_budget(path: str) -> Dict[str, int]:
    """Per-rig-config program-count bounds (the compile-explosion
    ratchet) from the same baseline file; missing file/key = no
    bounds recorded yet."""
    return {str(k): int(v) for k, v in
            _load_raw(path).get("program_budget", {}).items()}


def save_baseline(path: str, fingerprints: Iterable[str],
                  program_budget: Optional[Dict[str, int]] = None
                  ) -> None:
    """Write the baseline.  ``program_budget=None`` preserves the
    file's existing budget section untouched — the finding ratchet and
    the program-count ratchet shrink independently."""
    if program_budget is None:
        program_budget = load_program_budget(path)
    data: Dict[str, Any] = {"version": 1,
                            "findings": sorted(set(fingerprints))}
    if program_budget:
        data["program_budget"] = {k: int(program_budget[k])
                                  for k in sorted(program_budget)}
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def shrink_program_budget(path: str, counts: Dict[str, int],
                          known: Optional[Set[str]] = None
                          ) -> Dict[str, int]:
    """Ratchet-only budget update: for every config the auditor
    MEASURED this run, record ``min(stored, measured)`` — a bound can
    initialize (absent key) and shrink, never grow; growing past the
    bound means fixing the program explosion or hand-editing the JSON
    (the same deliberate escape hatch as the findings list).  Configs
    not measured (e.g. a single-device box skipping the P=2 rig) keep
    their stored bounds.  ``known``, when given, is the full rig
    config-name set: bounds for configs that no longer EXIST (renamed
    or removed rigs — not merely unhosted on this box) are dropped,
    the budget analogue of a stale finding fingerprint.  Returns the
    budget written."""
    budget = load_program_budget(path)
    if known is not None:
        budget = {k: v for k, v in budget.items() if k in known}
    for cfg, n in counts.items():
        budget[cfg] = min(budget.get(cfg, int(n)), int(n))
    save_baseline(path, load_baseline(path), program_budget=budget)
    return budget


def _rule_of(fingerprint: str) -> str:
    return fingerprint.split("|", 1)[0]


def split_findings(findings: List[Finding], baseline: Set[str],
                   active_rules: Optional[Set[str]] = None
                   ) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """``(new, baselined, stale)``: findings not covered by the
    baseline, findings the baseline tolerates, and baseline entries
    that no longer fire (candidates for the shrink ratchet).

    ``active_rules`` names the rules that actually RAN: baseline
    entries of rules outside it are never reported stale — a
    ``--select`` run must not declare findings it never looked for
    as gone."""
    new = [f for f in findings if f.fingerprint not in baseline]
    old = [f for f in findings if f.fingerprint in baseline]
    stale = baseline - {f.fingerprint for f in findings}
    if active_rules is not None:
        stale = {fp for fp in stale if _rule_of(fp) in active_rules}
    return new, old, stale


def shrink_baseline(path: str, findings: List[Finding],
                    active_rules: Optional[Set[str]] = None
                    ) -> Set[str]:
    """Ratchet-only update: rewrite ``path`` dropping entries that
    stopped firing — new findings are never absorbed (fix them or
    pragma them; hand-editing the JSON is the deliberate escape
    hatch).  Entries of rules outside ``active_rules`` are kept
    untouched: a selective run only ratchets what it measured.
    Returns the fingerprints written."""
    baseline = load_baseline(path)
    current = {f.fingerprint for f in findings}
    kept = {fp for fp in baseline
            if fp in current
            or (active_rules is not None
                and _rule_of(fp) not in active_rules)}
    save_baseline(path, kept)
    return kept
