"""Finding + baseline ratchet shared by every lint layer.

A finding's *fingerprint* is its identity for baseline matching:
``rule|unit|key`` with a rule-chosen ``key`` that stays stable across
line-number drift and re-runs (shapes and symbols, never line numbers
or wall-clock quantities).  The baseline (``scripts/lint_baseline.json``)
is ratchet-only: :func:`shrink_baseline` can DROP entries that no
longer fire, never add — new findings must be fixed (or suppressed at
the call site with an explanatory ``# roc-lint: ok=<rule>`` pragma),
exactly the lint_prints.sh contract this generalizes.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple


@dataclass
class Finding:
    """One lint hit.  ``unit`` locates the artifact: a repo-relative
    source path for AST rules, ``jaxpr:<step name>`` / ``hlo:<step
    name>`` for trace rules.  ``key`` overrides the fingerprint tail
    (defaults to ``msg`` — rules whose messages embed varying numbers
    must pass a stable key)."""

    rule: str
    unit: str
    msg: str
    line: Optional[int] = None
    key: Optional[str] = None
    detail: Dict[str, Any] = field(default_factory=dict)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.unit}|{self.key or self.msg}"

    def render(self) -> str:
        loc = f"{self.unit}:{self.line}" if self.line else self.unit
        return f"{loc}: [{self.rule}] {self.msg}"


def dedupe(findings: Iterable[Finding]) -> List[Finding]:
    """Drop findings with duplicate fingerprints (e.g. the same upcast
    eqn appearing in forward and recomputed-backward jaxprs) keeping
    first occurrence order."""
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            out.append(f)
    return out


def load_baseline(path: str) -> Set[str]:
    """Fingerprint set from a baseline file; a missing file is an
    empty baseline (the ratchet starts at zero)."""
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return set(data.get("findings", []))


def save_baseline(path: str, fingerprints: Iterable[str]) -> None:
    with open(path, "w") as f:
        json.dump({"version": 1,
                   "findings": sorted(set(fingerprints))}, f, indent=2)
        f.write("\n")


def _rule_of(fingerprint: str) -> str:
    return fingerprint.split("|", 1)[0]


def split_findings(findings: List[Finding], baseline: Set[str],
                   active_rules: Optional[Set[str]] = None
                   ) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """``(new, baselined, stale)``: findings not covered by the
    baseline, findings the baseline tolerates, and baseline entries
    that no longer fire (candidates for the shrink ratchet).

    ``active_rules`` names the rules that actually RAN: baseline
    entries of rules outside it are never reported stale — a
    ``--select`` run must not declare findings it never looked for
    as gone."""
    new = [f for f in findings if f.fingerprint not in baseline]
    old = [f for f in findings if f.fingerprint in baseline]
    stale = baseline - {f.fingerprint for f in findings}
    if active_rules is not None:
        stale = {fp for fp in stale if _rule_of(fp) in active_rules}
    return new, old, stale


def shrink_baseline(path: str, findings: List[Finding],
                    active_rules: Optional[Set[str]] = None
                    ) -> Set[str]:
    """Ratchet-only update: rewrite ``path`` dropping entries that
    stopped firing — new findings are never absorbed (fix them or
    pragma them; hand-editing the JSON is the deliberate escape
    hatch).  Entries of rules outside ``active_rules`` are kept
    untouched: a selective run only ratchets what it measured.
    Returns the fingerprints written."""
    baseline = load_baseline(path)
    current = {f.fingerprint for f in findings}
    kept = {fp for fp in baseline
            if fp in current
            or (active_rules is not None
                and _rule_of(fp) not in active_rules)}
    save_baseline(path, kept)
    return kept
