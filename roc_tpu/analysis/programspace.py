"""Program-space auditor: enumerate the compiled-program set WITHOUT
compiling.

The compile wall is a *program count* problem before it is a compile
speed problem: every ObservedJit slot, every streamed-head block
variant, and every quantized partition shape compiles its own XLA
program, and nothing short of a live run ever said how many programs a
config implies.  This level walks the SAME resolvers the trainers use
(``train/trainer.resolve_config``: fuse / auto-impl probe / memory
autopilot / attention impl — plus prefetch and partition method),
builds the rig trainers (table construction only — jits are lazy,
nothing compiles; the built plans pass through the splitter's
``core/partition.quantize_plan_shapes``, which is what keeps the
enumerated shapes and the trainers' real shapes in agreement), and
abstract-evals each candidate step to its canonical **program key**
``(slot, avals, shardings, donation)`` — the same
``obs/compile_watch.program_key_of`` every ObservedJit ``compile``
event now records, so the static enumeration is held against live
runs exactly (tests/test_programspace.py parity).

Products:

- a per-config **compile budget report** (program count x a coarse
  modeled compile cost), emitted as ``programspace`` obs events and
  rendered by ``roc_tpu.report``;
- [compile-explosion] — program count over the baselined bound for a
  rig config (``scripts/lint_baseline.json`` ``program_budget``,
  shrink-only like every ratchet): the static tripwire for the
  ROADMAP's compile-wall item — a PR that adds a compiled-program
  shape fails HERE, before any chip time;
- [cache-key-drift] — two program keys that differ ONLY by dimensions
  that snap to the same node- or edge-multiple (the
  ``NODE_MULTIPLE``/``EDGE_MULTIPLE`` grid ``quantize_plan_shapes``
  quantizes every plan to; the drift snap checks dims against that
  grid directly — it does not re-run the per-part plan derivation).
  Such a pair means an unquantized shape LEAKED around
  ``quantize_plan_shapes`` into one of the slots: wherever that slot's
  trace is rebuilt at a slightly different size (rebalance, resume,
  serve), the shape lands off the quantization grid and misses the
  persistent compile cache — the recompile class the PR-5 machinery
  exists to avoid.  The cross-slot comparison is the static proxy
  (one enumeration sees each slot once; the leaked dim shows up as
  disagreement BETWEEN slots that share their tensors).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.partition import EDGE_MULTIPLE, NODE_MULTIPLE, _round_up
from ..obs.events import emit
from .findings import Finding
from .jaxpr_lint import iter_eqns

# rig scale — THE synthetic-rig dimensions; driver.py imports these
# so the auditor and the jaxpr lint stage can never check different
# rigs
_V, _DEG, _F, _C, _H = 256, 6, 48, 6, 24

PROGRAMSPACE_RULES = ("compile-explosion", "cache-key-drift")

# Coarse affine compile-cost model, CPU-rig derived: a trivial jit is
# ~100 ms of fixed XLA pipeline overhead and cost grows roughly
# linearly in traced eqn count at small scale.  The report needs
# ORDERING between configs and a human-scale number, not accuracy —
# the ratchet is on the program COUNT.
COMPILE_MS_BASE = 100.0
COMPILE_MS_PER_EQN = 2.0


@dataclass(frozen=True)
class ProgramEntry:
    """One program the config will compile.  ``observed`` marks slots
    that compile through ObservedJit (the live-parity set); aux
    programs (streamed-head block jits) are counted in the budget but
    leave no ``compile`` event."""

    slot: str
    key: str                      # obs/compile_watch.program_key_of
    leaves: Tuple[Tuple[str, Tuple[int, ...], str], ...]
    observed: bool
    eqns: int

    @property
    def digest(self) -> str:
        return hashlib.sha1(self.key.encode()).hexdigest()[:12]

    @property
    def modeled_compile_ms(self) -> float:
        return COMPILE_MS_BASE + COMPILE_MS_PER_EQN * self.eqns


@dataclass
class ProgramSpace:
    """The enumerated program set of one rig config."""

    config: str
    entries: List[ProgramEntry]
    node_multiple: int = NODE_MULTIPLE
    edge_multiple: int = EDGE_MULTIPLE
    resolved: Dict[str, Any] = field(default_factory=dict)

    @property
    def program_count(self) -> int:
        return len(self.entries)

    def observed_keys(self) -> set:
        return {e.key for e in self.entries if e.observed}

    def modeled_compile_ms(self) -> float:
        return round(sum(e.modeled_compile_ms for e in self.entries), 1)

    def report(self, budget: Optional[int] = None) -> Dict[str, Any]:
        """The compile-budget record: the ``programspace`` event body,
        the report table row, and the ``--json`` payload."""
        rep: Dict[str, Any] = {
            "config": self.config,
            "programs": self.program_count,
            "observed_programs": len(self.observed_keys()),
            "modeled_compile_ms": self.modeled_compile_ms(),
            "slots": [e.slot for e in self.entries],
            "digests": [e.digest for e in self.entries],
            "budget": budget,
        }
        if budget is not None:
            rep["delta"] = self.program_count - budget
        return rep


@dataclass
class RigSpec:
    """One audited rig configuration: a model builder + TrainConfig
    factory + mesh width.  Factories (not instances) because a spec is
    enumerated, parity-tested, and idempotency-checked independently —
    each build must start from a pristine config.

    ``serve`` names a serving backend instead of a trainer: the rig
    then builds a ``roc_tpu/serve`` Predictor (same resolve pass, so
    the idempotency assert still applies) and the enumerated set is
    its bucketed serve-program space — which is how the serve tier's
    programs fall under the SAME ``program_budget`` ratchet and
    prewarm driver as the training steps.  ``quant`` selects the serve
    table encoding (``serve/quant.py``): quantized variants are
    DISTINCT programs with distinct slots (``_q8``/``_qf8``), so they
    get their own rig + budget row instead of inflating the fp32
    rig's."""

    name: str
    model: Callable[[], Any]
    config: Callable[[], Any]
    parts: int = 1
    serve: Optional[str] = None
    quant: str = "off"


def _rig_specs() -> Dict[str, RigSpec]:
    import jax.numpy as jnp

    from ..models.gin import build_gin
    from ..models.sgc import build_sgc
    from ..train.trainer import TrainConfig

    return {
        # GIN through the uniform width-8 FLAT-SUM layout on a
        # 2-device mesh: the sum-path uniform-scan consolidation
        # (ops/aggregate.py aggregate_flat_sum — ONE scan program per
        # aggregation width instead of one per degree bucket), and
        # the quantized-partition-shape config (the PR-5 splitter's
        # node/edge multiples are load-bearing in these program keys)
        "gin_flat8": RigSpec(
            name="gin_flat8",
            model=lambda: build_gin([_F, _H, _C], dropout_rate=0.5),
            config=lambda: TrainConfig(
                verbose=False, symmetric=True, aggr_impl="flat_sum",
                dtype=jnp.float32, compute_dtype=jnp.bfloat16),
            parts=2),
        # SGC with host-streamed features: the config whose program
        # space is NOT just the ObservedJit slots — the streamed head
        # compiles per-block-shape static variants too
        "sgc_stream": RigSpec(
            name="sgc_stream",
            model=lambda: build_sgc([_F, _C], k=2, dropout_rate=0.5),
            config=lambda: TrainConfig(
                verbose=False, symmetric=True, features="host",
                dtype=jnp.float32, compute_dtype=jnp.bfloat16),
            parts=1),
        # the serving tier (roc_tpu/serve): the SGC precomputed-
        # propagation predictor's bucketed program set — one program
        # per microbatch bucket, nothing else.  Enumerated here so a
        # PR that grows the serve program space (a new bucket, an
        # unquantized request shape) trips the compile-explosion
        # ratchet before any chip time, and so `python -m
        # roc_tpu.prewarm --config all` AOT-warms the serve
        # executables alongside the training steps.
        "sgc_serve": RigSpec(
            name="sgc_serve",
            model=lambda: build_sgc([_F, _C], k=2, dropout_rate=0.5),
            config=lambda: TrainConfig(
                verbose=False, symmetric=True, dtype=jnp.float32),
            parts=1, serve="precomputed"),
        # the QUANTIZED serve variant (PR 19): the same predictor
        # under int8 tables — the dequant-in-register bucket programs
        # (`serve_precomputed_akx_q8:{b}`) are a distinct program set
        # with distinct arg avals (int8 codes + fp32 scales), so they
        # ratchet under their own budget row while `sgc_serve` stays
        # at delta +0, and the prewarm driver AOT-warms the quantized
        # executables the export/cold-load path reuses.
        "sgc_serve_q8": RigSpec(
            name="sgc_serve_q8",
            model=lambda: build_sgc([_F, _C], k=2, dropout_rate=0.5),
            config=lambda: TrainConfig(
                verbose=False, symmetric=True, dtype=jnp.float32),
            parts=1, serve="precomputed", quant="int8"),
        # the (parts, model) 2-D mesh rig: gin_flat8's exact program
        # set widened to mesh=2x4 — params/Adam moments model-sharded
        # at rest, the partial-auto steps take the extra partition-
        # index arg, and every param/opt leaf's rendered sharding spec
        # lands in the program keys.  Needs 8 devices (parts * model
        # — rig_required_devices), so single-device CI skips it the
        # same way it skips parts > 1.
        "gin_mesh2d": RigSpec(
            name="gin_mesh2d",
            model=lambda: build_gin([_F, _H, _C], dropout_rate=0.5),
            config=lambda: TrainConfig(
                verbose=False, symmetric=True, aggr_impl="flat_sum",
                mesh="2x4",
                dtype=jnp.float32, compute_dtype=jnp.bfloat16),
            parts=2),
    }


RIG_CONFIGS: Dict[str, RigSpec] = {}


def rig_configs() -> Dict[str, RigSpec]:
    """Lazily built so importing the module never touches jax."""
    if not RIG_CONFIGS:
        RIG_CONFIGS.update(_rig_specs())
    return RIG_CONFIGS


def rig_required_devices(spec: RigSpec) -> int:
    """Total devices this spec's mesh occupies: ``parts * model``
    (``train/trainer.resolve_mesh`` on the spec's own config).  THE
    device guard every rig walker shares — the audit loop here,
    sharding_lint's rig sweep, and the prewarm driver — so a 2-D rig
    is skipped (not crashed) on hosts with too few devices, by the
    same rule everywhere."""
    from ..train.trainer import resolve_mesh
    parts = max(spec.parts, 1)
    _, model = resolve_mesh(spec.config(), num_parts=parts)
    return parts * model


def build_rig_dataset():
    from ..core.graph import synthetic_dataset
    return synthetic_dataset(num_nodes=_V, avg_degree=_DEG, in_dim=_F,
                             num_classes=_C, seed=0)


def build_rig_trainer(spec: RigSpec, dataset=None):
    """The trainer (or, for serve rigs, the Predictor) a live run of
    this spec would construct — table builds only; every jit slot
    stays uncompiled until called."""
    ds = dataset if dataset is not None else build_rig_dataset()
    if spec.serve:
        from ..serve.export import build_predictor
        return build_predictor(spec.model(), ds, spec.config(),
                               backend=spec.serve, quant=spec.quant)
    if spec.parts > 1:
        from ..parallel.distributed import DistributedTrainer
        return DistributedTrainer(spec.model(), ds, spec.parts,
                                  spec.config())
    from ..train.trainer import Trainer
    return Trainer(spec.model(), ds, spec.config())


def _count_eqns(closed_jaxpr) -> int:
    return sum(1 for _ in iter_eqns(closed_jaxpr))


def _entry(slot: str, fn, args, donate: Tuple[int, ...] = (),
           observed: bool = True) -> ProgramEntry:
    """Abstract-eval one candidate program: the key comes from the
    args' avals (the identical derivation ObservedJit applies at first
    compile) and the eqn count from a trace — ``jax.make_jaxpr`` never
    invokes the XLA pipeline, so this is the no-compile walk the
    auditor promises."""
    import jax

    from ..obs.compile_watch import leaf_struct, program_key_of
    key = program_key_of(slot, args, donate)
    # leaf_struct is compile_watch's OWN extraction (the rendered key
    # is built from it), so the drift rule's dimension view and the
    # parity keys can never disagree
    leaves = tuple(leaf_struct(v)
                   for v in jax.tree_util.tree_leaves(args))
    eqns = _count_eqns(jax.make_jaxpr(fn)(*args))
    return ProgramEntry(slot=slot, key=key, leaves=leaves,
                        observed=observed, eqns=eqns)


def _assert_resolve_idempotent(spec: RigSpec, dataset) -> None:
    """The resolve pass must be a fixpoint: re-resolving a resolved
    config changes nothing, hence re-enumerating yields the identical
    program-key set (the round-5 advisor's resolve finding, closed
    structurally).  Asserted on every audit — a resolver edit that
    breaks this would silently fork the auditor from the trainers."""
    from ..train.trainer import resolve_config
    model1, cfg1, _ = resolve_config(spec.model(), dataset,
                                     spec.config(),
                                     num_parts=spec.parts)
    model2, cfg2, _ = resolve_config(model1, dataset, cfg1,
                                     num_parts=spec.parts)
    if cfg1 != cfg2:
        raise AssertionError(
            f"resolve_config is not idempotent for rig "
            f"{spec.name!r}: {cfg1} != {cfg2}")
    if model2 is not model1:
        raise AssertionError(
            f"resolve_config re-rewrote an already-resolved model "
            f"for rig {spec.name!r}")


@dataclass
class Candidate:
    """One candidate compiled program of a trainer's lifecycle: the
    traceable callable + args the auditor abstract-evals to a program
    key, PLUS the zero-arg AOT compile closure (``aot``) the cache
    prewarm driver executes (utils/prewarm.py) — one extraction, two
    consumers, so the enumerated set and the warmed set can never
    drift.  ``aot`` goes through the SAME jitted callable a live run
    compiles (``jit.lower(*args).compile()``), so the persistent-cache
    entry it writes is exactly the one the live process will hit."""

    slot: str
    fn: Any
    args: tuple
    donate: Tuple[int, ...] = ()
    observed: bool = True
    aot: Optional[Callable[[], Any]] = None
    # per-top-level-arg semantic labels for the sharding auditor's
    # ledger/seeding ("params" / "opt_state" / "data" / "tables" /
    # "other"); () = classify by shape alone.  One enumeration, three
    # consumers (program keys, prewarm, replication ledger) — the
    # roles live on the record so they can never drift from the args.
    roles: Tuple[str, ...] = ()


def candidate_programs(tr) -> List["Candidate"]:
    """The exact candidate-program list of a trainer's
    train+eval+predict lifecycle (``run_epoch_loop`` + ``predict()``
    — note predict compiles NOTHING of its own since it reuses the
    eval program's logits output; the multi-process-only
    ``dist_predict_gather`` is out of scope for single-controller
    rigs).  Works on any built trainer — the audited rigs AND live
    bench trainers (utils/prewarm.warm_trainer)."""
    import jax
    import jax.numpy as jnp

    lr = jnp.asarray(0.01, jnp.float32)
    cands: List[Candidate] = []

    if hasattr(tr, "serve_candidates"):          # serve Predictor
        return list(tr.serve_candidates())

    def add(slot, jitfn, args, donate=(), observed=True, roles=()):
        cands.append(Candidate(
            slot=slot, fn=jitfn, args=args, donate=donate,
            observed=observed, roles=roles,
            aot=lambda j=jitfn, a=args: j.lower(*a).compile()))

    if getattr(tr, "pg", None) is not None:       # distributed
        d = tr.data
        fuse = (d.ell_w, d.sect_w, d.ring_w, d.bd_scale)
        graph_args = (d.edge_src, d.edge_dst, d.in_degree, d.ell_idx,
                      d.ell_row_pos, d.ell_row_id, d.ring_idx,
                      d.sect_idx, d.sect_sub_dst, d.bd_tabs, fuse)
        graph_roles = ("tables",) * len(graph_args)
        # 2-D partial-auto steps take the trailing parts-sharded
        # partition-index vector (distributed._build_steps); the
        # enumerated args must carry it or the keys (and make_jaxpr
        # arity) diverge from the live programs
        pids = (() if getattr(tr, "_pids", None) is None
                else (tr._pids,))
        pid_roles = ("data",) * len(pids)
        add("dist_train_step", tr._train_step._jit,
            (tr.params, tr.opt_state, d.feats, d.labels, d.mask)
            + graph_args + (tr.key, lr) + pids, donate=(0, 1),
            roles=("params", "opt_state", "data", "data", "data")
            + graph_roles + ("other", "other") + pid_roles)
        add("dist_eval_step", tr._eval_step._jit,
            (tr.params, d.feats, d.labels, d.mask) + graph_args
            + pids,
            roles=("params", "data", "data", "data") + graph_roles
            + pid_roles)
    elif tr._head is None:                        # plain single-device
        add("train_step", tr._train_step._jit,
            (tr.params, tr.opt_state, tr.key, lr, tr.feats,
             tr.labels, tr.mask, tr.gctx), donate=(0, 1),
            roles=("params", "opt_state", "other", "other", "data",
                   "data", "data", "tables"))
        add("eval_step", tr._eval_step._jit,
            (tr.params, tr.feats, tr.labels, tr.mask, tr.gctx),
            roles=("params", "data", "data", "data", "tables"))
    else:                                         # streamed head
        # abstract stand-ins, never materialized: [V, H] at the >HBM
        # tier is multi-GB, and warm_trainer runs this on LIVE bench
        # trainers whose aot closures would otherwise pin the buffers
        # alive for the whole warm loop.  leaf_struct renders a
        # ShapeDtypeStruct identically to a default-placed array
        # (spec '-'), and both make_jaxpr and jit.lower accept them,
        # so keys and prewarmed executables are unchanged.
        w0 = tr.params[tr._head_param]
        y = jax.ShapeDtypeStruct(
            (tr.feats_host.shape[0], int(w0.shape[1])),
            jnp.dtype(tr.compute))
        grads = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype),
            tr.params)
        # y is the streamed-head [V, H] handoff — role "stream", not
        # "data": it carries the FEATURE axis, so the sharding ledger
        # treats it as parts-split AND model-shardable (the 2-D mesh's
        # block path, train/trainer._pin_stream), unlike node-axis
        # data rows
        add("tail_grad", tr._tail_grad._jit,
            (tr.params, y, tr.key, tr.labels, tr.mask, tr.gctx),
            donate=(1,),
            roles=("params", "stream", "other", "data", "data",
                   "tables"))
        add("tail_eval", tr._tail_eval._jit,
            (tr.params, y, tr.labels, tr.mask, tr.gctx),
            roles=("params", "stream", "data", "data", "tables"))
        add("apply_update", tr._apply_update._jit,
            (tr.params, tr.opt_state, grads, lr),
            donate=(0, 1, 2),
            roles=("params", "opt_state", "data", "other"))
        cands.extend(_head_block_candidates(tr, y))
    return cands


def enumerate_programs(spec: RigSpec, dataset=None,
                       trainer=None) -> ProgramSpace:
    """The exact set of distinct programs a train+eval+predict
    lifecycle of ``spec`` compiles — the audited lifecycle is the one
    ``run_epoch_loop`` + ``predict()`` executes, which is also what
    the parity test drives live."""
    ds = dataset if dataset is not None else build_rig_dataset()
    _assert_resolve_idempotent(spec, ds)
    tr = trainer if trainer is not None else build_rig_trainer(
        spec, ds)
    entries = [_entry(c.slot, c.fn, c.args, c.donate, c.observed)
               for c in candidate_programs(tr)]
    # single-device rigs build no partition plan; the drift rule
    # still snaps against the SAME default grid the splitter uses
    nm, em = NODE_MULTIPLE, EDGE_MULTIPLE
    if spec.parts > 1:
        nm, em = tr.pg.node_multiple, tr.pg.edge_multiple
    space = ProgramSpace(
        config=spec.name, entries=entries,
        node_multiple=nm, edge_multiple=em,
        resolved={"aggr_impl": tr.config.aggr_impl,
                  "halo": tr.config.halo,
                  "features": tr.config.features,
                  "remat": tr.config.remat,
                  "partition": tr.config.partition,
                  "parts": spec.parts})
    _check_distinct(space)
    return space


def _head_block_candidates(tr, y) -> List["Candidate"]:
    """The streamed head's per-block jit variants — one program per
    distinct (block rows, train/eval statics) pair: uniform blocks
    share one compile, a ragged tail block adds one, and the forward
    compiles separately for the train (dropout-keyed) and eval paths.
    These are module-level ``jax.jit``s, not ObservedJit slots, so
    they appear in the budget with ``observed=False``.  Their ``aot``
    closures lower the REAL jitted block fns (statics passed
    positionally, the dynamic ``lo`` offset as a traced arg exactly
    like the live call) so the prewarmed executables byte-match the
    live ones in the persistent cache."""
    import jax
    import jax.numpy as jnp

    from ..core.streaming import _head_fwd_block, _head_wgrad_block
    w0 = tr.params[tr._head_param].astype(tr.compute)
    rate = tr._head.rate
    cands: List[Candidate] = []
    # y rows == the audited dataset's node count (NOT the rig
    # constant): enumeration must hold for whatever dataset the
    # trainer was built from
    sizes = sorted({hi - lo
                    for lo, hi in tr._head._blocks(y.shape[0])})
    dW = jax.ShapeDtypeStruct((int(w0.shape[0]), int(y.shape[1])),
                              jnp.dtype(jnp.float32))
    for rows in sizes:
        x = jax.ShapeDtypeStruct((rows, w0.shape[0]),
                                 jnp.dtype(tr.compute))
        for mode, use_mask, key in (("train", True, tr.key),
                                    ("eval", False, None)):
            cands.append(Candidate(
                slot=f"head_fwd_block:{rows}:{mode}",
                fn=(lambda xx, ww, kk, u=use_mask: _head_fwd_block(
                    xx, ww, rate, kk, u)),
                args=(x, w0, key), observed=False,
                roles=("data", "params", "other"),
                aot=(lambda xx=x, kk=key, u=use_mask:
                     _head_fwd_block.lower(
                         xx, w0, rate, kk, u).compile())))
        cands.append(Candidate(
            slot=f"head_wgrad_block:{rows}",
            fn=(lambda dw, xx, dy, kk, r=rows: _head_wgrad_block(
                dw, xx, dy, 0, r, rate, kk, True)),
            args=(dW, x, y, tr.key), observed=False,
            roles=("params", "data", "data", "other"),
            aot=(lambda xx=x, r=rows: _head_wgrad_block.lower(
                dW, xx, y, 0, r, rate, tr.key, True).compile())))
    return cands


def _check_distinct(space: ProgramSpace) -> None:
    keys = [e.key for e in space.entries]
    if len(set(keys)) != len(keys):
        dup = sorted(k for k in set(keys) if keys.count(k) > 1)
        raise AssertionError(
            f"program-space enumeration for {space.config!r} produced "
            f"duplicate keys: {dup[:2]} — two slots would compile the "
            f"same program; the enumeration (or a slot) is wrong")


# --------------------------------------------------------------- rules

def check_compile_explosion(space: ProgramSpace,
                            budget: Optional[int]) -> List[Finding]:
    """[compile-explosion] see module docstring.  ``budget`` is the
    baselined bound (``program_budget`` in scripts/lint_baseline.json,
    shrink-only); None means no bound is recorded yet — the CLI notes
    it and ``--update-baseline`` initializes it."""
    if budget is None or space.program_count <= budget:
        return []
    return [Finding(
        "compile-explosion", f"programspace:{space.config}",
        f"{space.program_count} distinct XLA programs exceed the "
        f"baselined bound {budget} (modeled compile "
        f"{space.modeled_compile_ms() / 1e3:.1f}s) — a new compiled-"
        f"program shape entered this config; consolidate the shape "
        f"(quantize/uniform-scan) or ratchet deliberately by "
        f"hand-editing program_budget",
        key="over-budget",
        detail={"programs": space.program_count, "budget": budget,
                "slots": [e.slot for e in space.entries]})]


def _drift_dims(a: ProgramEntry, b: ProgramEntry, nm: int,
                em: int) -> Optional[List[Tuple[int, int]]]:
    """The differing dims when ``a`` and ``b`` differ ONLY by
    dimensions that snap to the same node- or edge-multiple; None when
    they differ structurally (different programs for real reasons) or
    not at all."""
    if len(a.leaves) != len(b.leaves):
        return None
    diffs: List[Tuple[int, int]] = []
    for (d1, s1, sp1), (d2, s2, sp2) in zip(a.leaves, b.leaves):
        if d1 != d2 or sp1 != sp2 or len(s1) != len(s2):
            return None
        for x, y in zip(s1, s2):
            if x == y:
                continue
            node_tie = _round_up(x, nm) == _round_up(y, nm)
            # the edge-grid snap only counts as drift evidence when
            # the pair is not ALREADY on the node grid: two distinct
            # node-quantized dims (e.g. padded row counts 8 vs 120,
            # or hidden widths that are 8-multiples) land in the same
            # 128-window without any shape having leaked — flagging
            # them would be an unclearable finding, since there is
            # nothing left to quantize
            edge_tie = (_round_up(x, em) == _round_up(y, em)
                        and not (x % nm == 0 and y % nm == 0))
            if node_tie or edge_tie:
                diffs.append((x, y))
            else:
                return None
    return diffs or None


def check_cache_key_drift(space: ProgramSpace) -> List[Finding]:
    """[cache-key-drift] see module docstring.  Aux per-block
    programs (``observed=False`` — the streamed head's
    per-block-shape jit variants) are exempt on both sides of a pair:
    a ragged tail block legitimately differs from the uniform blocks
    by exactly a row count, and block sizes are not partition shapes —
    quantize_plan_shapes cannot (and should not) snap them, so
    flagging the pair would be a guaranteed false positive the gate
    could never clear."""
    out: List[Finding] = []
    es = [e for e in space.entries if e.observed]
    for i in range(len(es)):
        for j in range(i + 1, len(es)):
            diffs = _drift_dims(es[i], es[j], space.node_multiple,
                                space.edge_multiple)
            if diffs is None:
                continue
            ex = ", ".join(f"{x} vs {y}" for x, y in diffs[:3])
            out.append(Finding(
                "cache-key-drift", f"programspace:{space.config}",
                f"program keys of {es[i].slot!r} and {es[j].slot!r} "
                f"differ only by unquantized dimensions ({ex}) that "
                f"snap to the same node/edge multiple "
                f"({space.node_multiple}/{space.edge_multiple}) — an "
                f"unquantized shape leaked into one slot, and every "
                f"rebuild of it at a nearby size will miss the "
                f"persistent compile cache; route the shape through "
                f"core/partition.quantize_plan_shapes",
                key=f"drift|{es[i].slot}|{es[j].slot}"))
    return out


# --------------------------------------------------------------- stage

def audit_program_space(select: Optional[List[str]] = None,
                        program_budget: Optional[Dict[str, int]] = None,
                        extras: Optional[Dict[str, Any]] = None
                        ) -> List[Finding]:
    """Run the auditor over every rig config the backend can host.
    Emits one ``programspace`` event per config; when ``extras`` is a
    dict, appends the report records under ``extras['programspace']``
    (the CLI's budget print + ``--json`` payload)."""
    import jax

    budget = program_budget or {}
    findings: List[Finding] = []
    ds = None
    for name, spec in rig_configs().items():
        if rig_required_devices(spec) > len(jax.devices()):
            continue
        if ds is None:   # one synthetic rig dataset for every config
            ds = build_rig_dataset()
        space = enumerate_programs(spec, dataset=ds)
        rep = space.report(budget=budget.get(name))
        rep["keys"] = [e.key for e in space.entries]
        emit("programspace",
             f"program space {name}: {rep['programs']} programs "
             f"(modeled compile {rep['modeled_compile_ms'] / 1e3:.1f}s"
             f", baseline {rep['budget']})",
             console=False,
             **{k: v for k, v in rep.items() if k != "keys"})
        if extras is not None:
            extras.setdefault("programspace", []).append(rep)
        if select is None or "compile-explosion" in select:
            findings.extend(
                check_compile_explosion(space, budget.get(name)))
        if select is None or "cache-key-drift" in select:
            findings.extend(check_cache_key_drift(space))
    return findings
