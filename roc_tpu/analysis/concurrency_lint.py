"""Concurrency & signal-safety auditor — roc-lint level six.

The host-side runtime is no longer one SPMD step loop: a StagingPool
h2d worker (``core/streaming.py``), the Heartbeat watchdog
(``obs/heartbeat.py``), the coalescing ``Server._loop`` dispatcher
(``serve/server.py``), the event-bus locks (``obs/events.py``),
SIGTERM/SIGINT handlers (``resilience/preempt.py``), and bench's
stderr reader threads all run concurrently with the training/serving
main thread.  Every concurrency bug shipped so far was caught by hand
review *after* the fact — the non-signal-reentrant event-bus lock and
the ``interrupt_main``-never-delivered hang (PR 8), the open-loop
wake-before-callback race (PR 11).  This level makes that bug class a
ratcheted static gate, same contract as the other five.

The auditor parses the whole host-side tree (``roc_tpu/**/*.py`` plus
the repo-root ``bench.py`` and ``benchmarks/*.py``) ONCE into a
cross-module model of

- **lock objects** — ``threading.Lock/RLock/Condition`` bound to
  instance attributes (``self._lock = threading.Lock()``) or module
  globals (``_BUS_LOCK = threading.Lock()``); ``Event``/``Semaphore``
  are classified but are not locks (no lost-wakeup / ordering
  semantics of their own),
- **thread entry points** — ``threading.Thread(target=...)`` bodies,
  resolved to same-class methods, module functions, or local closures,
- **signal handlers** — ``signal.signal(sig, handler)`` registrations,

and checks six rules over it (``CONCURRENCY_RULES``).  Call graphs
are walked shallowly (handlers: one level; lock summaries: a small
bounded fixpoint) and attribute calls resolve only when unambiguous
(``self.m`` → the enclosing class; a bare ``obj.m`` only when exactly
one class in the tree defines ``m``) — the auditor prefers missing an
exotic alias to drowning the ratchet in false positives.

Held regions come in two shapes (ISSUE 13 satellite): ``with lock:``
blocks, and explicit ``lock.acquire()`` … ``lock.release()`` pairs —
statements between the pair at the same nesting level are modeled as
held, including the canonical ``acquire(); try: … finally:
release()`` idiom (the try body is the held region).  An ``acquire()``
whose release never appears in the same statement list holds to the
end of the list — conservative, and exactly what a leaked lock does.

Every rule suppresses per line with the standard self-documenting
pragma (``# <why>: roc-lint: ok=<rule>``), findings ride the same
shrink-only baseline ratchet, and the discovered surface (threads /
locks / handlers per module) is exported for ``--json`` and the
``roc_tpu.report`` "concurrency surface" table — the audit doubles as
documentation of the runtime's thread model.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from .ast_lint import pragma_ok
from .findings import Finding
from .protocol_specs import ckpt_artifact_entries

CONCURRENCY_RULES = (
    "signal-unsafe-handler",
    "lock-order-cycle",
    "condvar-wait-no-predicate",
    "unguarded-shared-state",
    "blocking-under-lock",
    "thread-no-shutdown-path",
    "artifact-lock-ownership",
)

# threading constructors that create an *acquirable mutual-exclusion*
# object (these participate in the ordering graph and the held-region
# checks) vs. other sync primitives (classified for the surface table
# and the shutdown-path rule, but not locks)
_LOCK_KINDS = {"Lock": "lock", "RLock": "rlock",
               "Condition": "condition"}
_OTHER_SYNC = {"Event": "event", "Semaphore": "semaphore",
               "BoundedSemaphore": "semaphore", "Barrier": "barrier"}

# mutating container methods: `self.xs.append(...)` in a thread body
# is a write to shared state exactly like `self.x = ...`
_MUTATORS = {"append", "extend", "insert", "add", "remove", "pop",
             "popleft", "appendleft", "clear", "update", "discard",
             "setdefault", "sort", "reverse"}

# callables that block (device round trips, file/process I/O, sleeps)
# — reachable while a lock is held they serialize every other holder
# behind one caller's wait: the stall class the runtime watchdog
# exists to catch, caught here at parse time instead
_BLOCKING_NAMES = {"device_put", "device_get", "block_until_ready",
                   "open"}
_BLOCKING_ATTRS = {"device_put", "device_get", "block_until_ready",
                   "write", "flush", "fsync", "result", "communicate",
                   "emit"}
_BLOCKING_QUALIFIED = {("time", "sleep"), ("subprocess", "run"),
                       ("subprocess", "Popen"),
                       ("subprocess", "call"),
                       ("subprocess", "check_call"),
                       ("subprocess", "check_output"),
                       ("os", "fsync")}

# calls sanctioned inside a signal handler: POSIX async-signal-safe
# (or flag-only) primitives the graceful-shutdown path legitimately
# needs — everything else that locks/allocates/does buffered I/O is
# the PR-8 bug class
_HANDLER_SAFE_QUALIFIED = {("signal", "signal"), ("os", "kill"),
                           ("os", "getpid"), ("time", "monotonic"),
                           ("time", "time"), ("time", "perf_counter")}
_HANDLER_SAFE_NAMES = {"int", "float", "str", "bool", "len",
                       "isinstance", "getattr", "KeyboardInterrupt",
                       "RuntimeError", "SystemExit"}


# --------------------------------------------------------------- model

@dataclass(eq=False)
class LockDef:
    """One sync object: a ``self.<name>`` attribute of ``cls`` or
    (``cls=None``) a module-level global."""
    module: str
    cls: Optional[str]
    name: str
    kind: str
    line: int

    @property
    def lock_id(self) -> str:
        owner = f"{self.cls}." if self.cls else ""
        return f"{self.module}:{owner}{self.name}"

    @property
    def is_lock(self) -> bool:
        return self.kind in ("lock", "rlock", "condition")


@dataclass(eq=False)
class FuncDef:
    module: str
    cls: Optional[str]
    qualname: str           # Class.method / func / outer.<locals>.f
    node: ast.AST

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module, self.qualname)


@dataclass(eq=False)
class ThreadStart:
    module: str
    cls: Optional[str]              # class whose method starts it
    func: Optional[str]             # qualname of the starting func
    node: ast.Call
    target: Optional[ast.AST]       # the target= expression
    daemon: bool
    name: Optional[str]
    store: Optional[Tuple[str, str]]  # ('attr'|'name', identifier)


@dataclass(eq=False)
class HandlerReg:
    module: str
    node: ast.Call
    handler: Optional[ast.AST]      # the handler expression
    cls: Optional[str]              # class context of the call site


@dataclass(eq=False)
class ModuleModel:
    rel: str
    tree: ast.Module
    lines: List[str]
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)
    # (cls-or-None, name) -> LockDef, every sync object incl. events
    sync: Dict[Tuple[Optional[str], str], LockDef] = \
        field(default_factory=dict)
    funcs: Dict[str, FuncDef] = field(default_factory=dict)
    imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    signal_aliases: Set[str] = field(default_factory=set)
    threads: List[ThreadStart] = field(default_factory=list)
    handlers: List[HandlerReg] = field(default_factory=list)
    thread_attrs: Set[Tuple[Optional[str], str]] = \
        field(default_factory=set)

    def lock(self, cls: Optional[str], name: str) -> Optional[LockDef]:
        return self.sync.get((cls, name))


class TreeModel:
    """Whole-tree parse: every scanned module's AST plus the derived
    lock/thread/handler indices the rules share."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleModel] = {}
        base = pathlib.Path(root)
        paths = sorted(base.glob("roc_tpu/**/*.py"))
        for extra in [base / "bench.py"]:
            if extra.exists():
                paths.append(extra)
        paths.extend(sorted(base.glob("benchmarks/*.py")))
        for path in paths:
            rel = path.relative_to(base).as_posix()
            src = path.read_text()
            self.modules[rel] = _build_module(
                rel, ast.parse(src, filename=rel), src.splitlines())
        # global indices
        self.locks_by_name: Dict[str, List[LockDef]] = {}
        self.methods_by_name: Dict[str, List[FuncDef]] = {}
        for m in self.modules.values():
            for ld in m.sync.values():
                self.locks_by_name.setdefault(ld.name, []).append(ld)
            for f in m.funcs.values():
                if f.cls and f.qualname == f"{f.cls}.{f.node.name}":
                    self.methods_by_name.setdefault(
                        f.node.name, []).append(f)
        self._acq_memo: Dict[Tuple[str, str], Set[str]] = {}
        self._region_memo: Dict[Tuple[str, str],
                                List[Tuple[str, "_HeldRegion"]]] = {}

    # ------------------------------------------------ name resolution

    def resolve_lock(self, mod: ModuleModel, expr: ast.AST,
                     cls: Optional[str]) -> Optional[str]:
        """Lock id for an acquisition expression, ``"?"`` for a
        lock-shaped attribute whose owner is ambiguous (held-region
        checks honor it; the ordering graph skips it), None when the
        expression is not a known lock."""
        if isinstance(expr, ast.Name):
            ld = mod.lock(None, expr.id)
            if ld is not None:
                return ld.lock_id if ld.is_lock else None
            imp = mod.imports.get(expr.id)
            if imp and imp[0] in self.modules:
                ld = self.modules[imp[0]].lock(None, imp[1])
                if ld is not None and ld.is_lock:
                    return ld.lock_id
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and cls is not None:
                ld = mod.lock(cls, expr.attr)
                if ld is not None:
                    return ld.lock_id if ld.is_lock else None
                return None
            cands = [ld for ld in self.locks_by_name.get(expr.attr, [])
                     if ld.is_lock]
            if len(cands) == 1:
                return cands[0].lock_id
            if len(cands) > 1:
                return "?"
        return None

    def resolve_call(self, mod: ModuleModel, call: ast.Call,
                     cls: Optional[str]) -> Optional[FuncDef]:
        """Callee FuncDef for a call node, shallow and conservative:
        same-module functions, ``from``-imported functions, ``self.m``
        methods, and ``obj.m`` only when exactly one class anywhere in
        the tree defines a method ``m``."""
        f = call.func
        if isinstance(f, ast.Name):
            fd = mod.funcs.get(f.id)
            if fd is not None:
                return fd
            imp = mod.imports.get(f.id)
            if imp and imp[0] in self.modules:
                return self.modules[imp[0]].funcs.get(imp[1])
            return None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and cls is not None:
                return mod.funcs.get(f"{cls}.{f.attr}")
            cands = self.methods_by_name.get(f.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    # --------------------------------------------- lock-acquire model

    def direct_acquires(self, fd: FuncDef) -> List[Tuple[str, ast.With]]:
        """(lock_id, held-region) for every lock acquisition in
        ``fd`` (``"?"`` kept): with-blocks, plus explicit
        ``acquire()``/``release()`` regions (:meth:`acquire_regions`).
        Both shapes expose a ``.body`` statement list, so every rule
        walking held regions covers them identically."""
        mod = self.modules[fd.module]
        out = []
        for node in _walk_own(fd.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self.resolve_lock(mod, item.context_expr,
                                            fd.cls)
                    if lid is not None:
                        out.append((lid, node))
        out.extend(self.acquire_regions(fd))
        return out

    def acquire_regions(self, fd: FuncDef
                        ) -> List[Tuple[str, "_HeldRegion"]]:
        """Explicit ``lock.acquire()`` … ``lock.release()`` held
        regions in ``fd``, one per acquire site (memoized): the
        statements between the pair at the same nesting level, or —
        the ``acquire(); try: … finally: release()`` idiom — the try
        body (+ handlers/orelse).  A missing release holds to the end
        of the statement list (that IS the leak)."""
        memo = self._region_memo.get(fd.key)
        if memo is not None:
            return memo
        mod = self.modules[fd.module]
        out: List[Tuple[str, _HeldRegion]] = []
        for lst in _stmt_lists(fd.node):
            for i, stmt in enumerate(lst):
                expr = _acquire_expr(stmt)
                if expr is None:
                    continue
                lid = self.resolve_lock(mod, expr, fd.cls)
                if lid is None:
                    continue
                nxt = lst[i + 1] if i + 1 < len(lst) else None
                if isinstance(nxt, ast.Try) and any(
                        self._is_release(mod, s, lid, fd.cls)
                        for s in nxt.finalbody):
                    body = (list(nxt.body)
                            + [s for h in nxt.handlers
                               for s in h.body]
                            + list(nxt.orelse))
                else:
                    body = []
                    for s in lst[i + 1:]:
                        if self._is_release(mod, s, lid, fd.cls):
                            break
                        body.append(s)
                out.append((lid, _HeldRegion(body, stmt.lineno)))
        self._region_memo[fd.key] = out
        return out

    def _is_release(self, mod: ModuleModel, stmt: ast.AST, lid: str,
                    cls: Optional[str]) -> bool:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "release"):
            return False
        return self.resolve_lock(mod, stmt.value.func.value,
                                 cls) == lid

    def trans_acquires(self, fd: FuncDef, _depth: int = 0,
                       _stack: Optional[Set[Tuple[str, str]]] = None,
                       _truncated: Optional[List[bool]] = None
                       ) -> Set[str]:
        """Locks ``fd`` may acquire, including through a bounded walk
        of resolvable callees (depth 4 — enough for the tree's
        ``emit -> get_bus -> EventLog.emit`` chain, small enough to
        stay milliseconds).  A result computed under a cycle cut or
        the depth cap is returned but NOT memoized — caching a
        truncated set as final would silently drop real
        acquired-while-holding edges on every later query (the
        mutual-recursion memo-poisoning bug the review fixture
        caught)."""
        memo = self._acq_memo.get(fd.key)
        if memo is not None:
            return memo
        if _stack is None:
            _stack = set()
        if _truncated is None:
            _truncated = [False]
        if fd.key in _stack or _depth > 4:
            _truncated[0] = True
            return set()
        _stack.add(fd.key)
        mod = self.modules[fd.module]
        out: Set[str] = {lid for lid, _ in self.direct_acquires(fd)
                         if lid != "?"}
        for node in _walk_own(fd.node):
            if isinstance(node, ast.Call):
                callee = self.resolve_call(mod, node, fd.cls)
                if callee is not None:
                    out |= self.trans_acquires(callee, _depth + 1,
                                               _stack, _truncated)
        _stack.discard(fd.key)
        if not _truncated[0]:
            self._acq_memo[fd.key] = out
        return out


class _HeldRegion:
    """A synthetic held-region node for an explicit ``acquire()``
    pair: quacks like ``ast.With`` where the rules care (``.body`` is
    the held statement list, ``.lineno`` the acquire site)."""

    __slots__ = ("body", "lineno")

    def __init__(self, body: List[ast.AST], lineno: int):
        self.body = body
        self.lineno = lineno


def _acquire_expr(stmt: ast.AST) -> Optional[ast.AST]:
    """The lock expression of a bare ``<lock>.acquire(...)`` statement
    (an ``if lock.acquire(timeout=...):`` guard is NOT modeled — the
    held region is conditional and the auditor prefers silence to a
    false edge)."""
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            return f.value
    return None


def _stmt_lists(func_node: ast.AST) -> Iterable[List[ast.AST]]:
    """Every statement list of a function body, WITHOUT descending
    into nested function definitions (their bodies are their own
    entry points, like :func:`_walk_own`)."""
    stack: List[ast.AST] = [func_node]
    while stack:
        node = stack.pop()
        for field in ("body", "orelse", "finalbody"):
            lst = getattr(node, field, None)
            if isinstance(lst, list) and lst \
                    and isinstance(lst[0], ast.stmt):
                yield lst
        for h in getattr(node, "handlers", None) or []:
            if h.body:
                yield h.body
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _walk_own(func_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested function
    definitions (a closure is its own entry point, not part of its
    definer's straight-line behavior)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _sync_kind(value: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition'/'event'/... when ``value`` is a
    ``threading.X()`` (or bare ``X()``) sync-object constructor."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in _LOCK_KINDS:
        return _LOCK_KINDS[name]
    if name in _OTHER_SYNC:
        return _OTHER_SYNC[name]
    return None


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    return ((isinstance(f, ast.Attribute) and f.attr == "Thread"
             and isinstance(f.value, ast.Name)
             and f.value.id == "threading")
            or (isinstance(f, ast.Name) and f.id == "Thread"))


def _const(expr: Optional[ast.AST]) -> Any:
    return expr.value if isinstance(expr, ast.Constant) else None


def _resolve_import_target(rel: str, node: ast.ImportFrom
                           ) -> Optional[str]:
    """Repo-relative ``.py`` path a ``from X import Y`` names (best
    effort; absolute imports of stdlib return a non-existent path the
    caller simply won't find in the model)."""
    parts = rel[:-3].split("/")
    if node.level:
        if node.level > len(parts):
            return None
        base = parts[:-node.level]
    else:
        base = []
    modparts = node.module.split(".") if node.module else []
    target = base + modparts
    if not target:
        return None
    return "/".join(target) + ".py"


def _build_module(rel: str, tree: ast.Module,
                  lines: List[str]) -> ModuleModel:
    m = ModuleModel(rel=rel, tree=tree, lines=lines)
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            m.parents[child] = node

    def _cls_of(node: ast.AST) -> Optional[str]:
        cur = node
        while cur in m.parents:
            cur = m.parents[cur]
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, ast.Module):
                return None
        return None

    # function registry with qualified names (Class.method for direct
    # methods; dotted <locals> chains for closures)
    def _register(node, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qn = (f"{prefix}.{child.name}" if prefix
                      else child.name)
                m.funcs[qn] = FuncDef(rel, cls, qn, child)
                _register(child, f"{qn}.<locals>", cls)
            elif isinstance(child, ast.ClassDef):
                _register(child, child.name, child.name)
            elif not isinstance(child, ast.Lambda):
                _register(child, prefix, cls)
    _register(tree, "", None)
    # closures also reachable by bare short name (thread targets are
    # started by name from their definer's scope); plain functions and
    # methods are NOT aliased — a bare call must never accidentally
    # resolve to some class's method
    for qn, fd in list(m.funcs.items()):
        short = qn.rsplit(".", 1)[-1]
        if "<locals>" in qn and short not in m.funcs:
            m.funcs[short] = fd

    # pass 1: imports and sync/thread-attr definitions (order-free
    # facts the second pass depends on)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            target = _resolve_import_target(rel, node)
            if target:
                for alias in node.names:
                    m.imports.setdefault(alias.asname or alias.name,
                                         (target, alias.name))
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "signal":
                    m.signal_aliases.add(alias.asname or "signal")
        elif isinstance(node, ast.Assign):
            kind = _sync_kind(node.value)
            cls = _cls_of(node)
            for tgt in node.targets:
                if kind and isinstance(tgt, ast.Name) and cls is None \
                        and isinstance(m.parents.get(node), ast.Module):
                    m.sync[(None, tgt.id)] = LockDef(
                        rel, None, tgt.id, kind, node.lineno)
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self" and cls:
                    if kind:
                        m.sync[(cls, tgt.attr)] = LockDef(
                            rel, cls, tgt.attr, kind, node.lineno)
                    if isinstance(node.value, ast.Call) \
                            and _is_thread_ctor(node.value):
                        m.thread_attrs.add((cls, tgt.attr))

    # pass 2: thread starts and signal-handler registrations (these
    # consult the alias/import facts above, so they need their own
    # walk — ast.walk order is not source order)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if _is_thread_ctor(node):
                kw = {k.arg: k.value for k in node.keywords}
                store = None
                parent = m.parents.get(node)
                if isinstance(parent, ast.Assign) \
                        and len(parent.targets) == 1:
                    t = parent.targets[0]
                    if isinstance(t, ast.Name):
                        store = ("name", t.id)
                    elif isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        store = ("attr", t.attr)
                m.threads.append(ThreadStart(
                    rel, _cls_of(node), _enclosing_func_qualname(m, node),
                    node, kw.get("target"),
                    bool(_const(kw.get("daemon"))),
                    _const(kw.get("name")), store))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "signal" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in (m.signal_aliases
                                               or {"signal"}) \
                    and len(node.args) == 2:
                m.handlers.append(HandlerReg(rel, node, node.args[1],
                                             _cls_of(node)))
    return m


def _enclosing_func_qualname(m: ModuleModel,
                             node: ast.AST) -> Optional[str]:
    """Registry qualname of the function lexically enclosing ``node``
    (``Class.method``, ``func``, ``outer.<locals>.inner``), or None at
    module scope."""
    chain: List[Tuple[str, str]] = []      # innermost-first
    cur = node
    while cur in m.parents:
        cur = m.parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            chain.append(("f", cur.name))
        elif isinstance(cur, ast.ClassDef):
            chain.append(("c", cur.name))
    while chain and chain[0][0] == "c":    # node sits in a class body
        chain.pop(0)
    if not chain:
        return None
    chain.reverse()
    qn = ""
    prev = None
    for kind, name in chain:
        if not qn:
            qn = name
        elif prev == "f":
            qn = f"{qn}.<locals>.{name}"
        else:
            qn = f"{qn}.{name}"
        prev = kind
    return qn if qn in m.funcs else None


def _enclosing_class(m: ModuleModel, node: ast.AST) -> Optional[str]:
    cur = node
    while cur in m.parents:
        cur = m.parents[cur]
        if isinstance(cur, ast.ClassDef):
            return cur.name
        if isinstance(cur, ast.Module):
            return None
    return None


def _enclosing_while(m: ModuleModel, node: ast.AST) -> bool:
    cur = node
    while cur in m.parents:
        cur = m.parents[cur]
        if isinstance(cur, ast.While):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            return False
    return False


def _held_lock(tm: TreeModel, m: ModuleModel, node: ast.AST,
               cls: Optional[str]) -> Optional[str]:
    """Lock id (or ``"?"``) of the innermost enclosing held region —
    a with-block, or an explicit ``acquire()``/``release()`` span —
    else None."""
    seen = {id(node)}
    cur = node
    while cur in m.parents:
        cur = m.parents[cur]
        seen.add(id(cur))
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                lid = tm.resolve_lock(m, item.context_expr, cls)
                if lid is not None:
                    return lid
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # explicit-pair regions of the enclosing function: the
            # node is held if any region statement is on its ancestor
            # chain (statements are the region's roots)
            qn = _enclosing_func_qualname(m, node)
            fd = m.funcs.get(qn) if qn else None
            if fd is not None:
                for lid, region in tm.acquire_regions(fd):
                    if any(id(s) in seen for s in region.body):
                        return lid
            return None
        if isinstance(cur, ast.Module):
            return None
    return None


# ------------------------------------------------- rule: signal safety

def _call_label(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        base = (f.value.id if isinstance(f.value, ast.Name)
                else "<expr>")
        return f"{base}.{f.attr}"
    return "<call>"


def _handler_violations(tm: TreeModel, m: ModuleModel, fd: FuncDef
                        ) -> List[Tuple[int, str]]:
    """(line, why) pairs for non-flag-safe work in one handler body."""
    out: List[Tuple[int, str]] = []
    for node in _walk_own(fd.node):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            out.append((node.lineno,
                        "import inside a signal handler (can deadlock"
                        " on the interpreter import lock)"))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lid = tm.resolve_lock(m, item.context_expr, fd.cls)
                if lid is not None:
                    out.append((node.lineno,
                                f"acquires lock {lid} (not "
                                f"signal-reentrant: the interrupted "
                                f"thread may hold it)"))
        elif isinstance(node, ast.Call):
            label = _call_label(node)
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name):
                q = (f.value.id, f.attr)
                if q in _HANDLER_SAFE_QUALIFIED:
                    continue
                if f.value.id in ("signal", "_signal"):
                    continue
            if isinstance(f, ast.Name) \
                    and f.id in _HANDLER_SAFE_NAMES:
                continue
            if isinstance(f, ast.Attribute) and f.attr == "acquire":
                out.append((node.lineno,
                            f"{label}() acquires a lock in a signal "
                            f"handler"))
            elif isinstance(f, ast.Name) and f.id == "emit" \
                    or isinstance(f, ast.Attribute) and f.attr == "emit":
                out.append((node.lineno,
                            f"{label}() emits on the event bus (bus "
                            f"lock is not signal-reentrant — the PR-8"
                            f" bug class)"))
            elif isinstance(f, ast.Name) and f.id in ("print", "open"):
                out.append((node.lineno,
                            f"{f.id}() does buffered I/O in a signal "
                            f"handler"))
            elif isinstance(f, ast.Attribute) \
                    and f.attr in ("write", "flush"):
                out.append((node.lineno,
                            f"{label}() does I/O in a signal handler"))
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy", "jnp", "jax"):
                out.append((node.lineno,
                            f"{label}() allocates/dispatches in a "
                            f"signal handler"))
    return out


def check_signal_handlers(tm: TreeModel) -> List[Finding]:
    """[signal-unsafe-handler] a registered handler's body (plus one
    level of resolvable callees) may only set/read flags: lock
    acquisition, event-bus emits, imports, buffered I/O, and
    numpy/jax allocation are flagged.  ``SIG_DFL``/``SIG_IGN`` and
    unresolvable handler expressions are skipped."""
    findings: List[Finding] = []
    for m in tm.modules.values():
        for reg in m.handlers:
            h = reg.handler
            fd: Optional[FuncDef] = None
            if isinstance(h, ast.Attribute):
                if h.attr in ("SIG_DFL", "SIG_IGN"):
                    continue
                if isinstance(h.value, ast.Name) \
                        and h.value.id == "self" and reg.cls:
                    fd = m.funcs.get(f"{reg.cls}.{h.attr}")
            elif isinstance(h, ast.Name):
                fd = m.funcs.get(h.id)
                if fd is None:
                    imp = m.imports.get(h.id)
                    if imp and imp[0] in tm.modules:
                        fd = tm.modules[imp[0]].funcs.get(imp[1])
            if fd is None:
                continue
            fmod = tm.modules[fd.module]
            # handler body + one level of resolvable callees
            bodies = [(fmod, fd)]
            for node in _walk_own(fd.node):
                if isinstance(node, ast.Call):
                    callee = tm.resolve_call(fmod, node, fd.cls)
                    if callee is not None:
                        bodies.append((tm.modules[callee.module],
                                       callee))
            for bm, bfd in bodies:
                for line, why in _handler_violations(tm, bm, bfd):
                    findings.append(Finding(
                        "signal-unsafe-handler", bm.rel,
                        f"signal handler {fd.qualname} "
                        + (f"(via {bfd.qualname}) " if bfd is not fd
                           else "")
                        + f"must only set/read flags: {why}",
                        line=line,
                        key=f"handler={fd.qualname},"
                            f"via={bfd.qualname}@{line}"))
    return findings


# ---------------------------------------------- rule: lock order graph

def build_lock_graph(tm: TreeModel
                     ) -> Dict[str, Dict[str, Tuple[str, int]]]:
    """acquired-while-holding edges: ``graph[A][B] = (module, line)``
    means some code path acquires B (directly or through a resolvable
    call chain) while holding A."""
    graph: Dict[str, Dict[str, Tuple[str, int]]] = {}
    for m in tm.modules.values():
        for fd in set(m.funcs.values()):
            for lid, wnode in tm.direct_acquires(fd):
                if lid == "?":
                    continue
                inner: Dict[str, int] = {}
                for node in _walk_body(wnode):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            nid = tm.resolve_lock(m, item.context_expr,
                                                  fd.cls)
                            if nid and nid not in ("?", lid):
                                inner.setdefault(nid, node.lineno)
                    elif isinstance(node, ast.Call):
                        f = node.func
                        if isinstance(f, ast.Attribute) \
                                and f.attr == "acquire":
                            # explicit nested acquire: an edge exactly
                            # like a nested with-block
                            nid = tm.resolve_lock(m, f.value, fd.cls)
                            if nid and nid not in ("?", lid):
                                inner.setdefault(nid, node.lineno)
                            continue
                        callee = tm.resolve_call(m, node, fd.cls)
                        if callee is not None:
                            for nid in tm.trans_acquires(callee):
                                if nid != lid:
                                    inner.setdefault(nid, node.lineno)
                for nid, line in inner.items():
                    graph.setdefault(lid, {}).setdefault(
                        nid, (m.rel, line))
    return graph


def _walk_body(wnode: ast.With) -> Iterable[ast.AST]:
    stack = list(wnode.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def check_lock_order(tm: TreeModel) -> List[Finding]:
    """[lock-order-cycle] a cycle in the acquired-while-holding graph
    is a potential deadlock: two threads entering the cycle from
    different edges block each other forever.  One finding per cycle,
    fingerprinted by the sorted lock set (stable across line drift).
    A pragma on any participating acquisition line suppresses the
    cycle (document WHY the ordering is safe — e.g. one of the locks
    is never contended cross-thread)."""
    graph = build_lock_graph(tm)
    findings: List[Finding] = []
    seen: Set[frozenset] = set()
    # iterative DFS cycle detection over a small graph
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, {})):
                if nxt == start:
                    cyc = frozenset(path)
                    if cyc in seen:
                        continue
                    seen.add(cyc)
                    edges = []
                    suppressed = False
                    ring = path + [start]
                    for a, b in zip(ring, ring[1:]):
                        mod, line = graph[a][b]
                        edges.append(f"{a} -> {b} ({mod}:{line})")
                        mm = tm.modules.get(mod)
                        if mm is not None and pragma_ok(
                                mm.lines, line, "lock-order-cycle"):
                            suppressed = True
                    if suppressed:
                        continue
                    mod0, line0 = graph[path[0]][ring[1]]
                    findings.append(Finding(
                        "lock-order-cycle", "concurrency:lock-graph",
                        "lock-ordering cycle (potential deadlock): "
                        + "; ".join(edges),
                        line=line0,
                        key="cycle=" + ",".join(sorted(cyc))))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return findings


# ------------------------------------- rule: condvar wait w/o predicate

def check_condvar_predicates(tm: TreeModel) -> List[Finding]:
    """[condvar-wait-no-predicate] ``Condition.wait()`` outside a
    ``while``-predicate loop loses wakeups: a notify that fires
    between the caller's predicate check and the wait blocks forever
    (the PR-11 open-loop race class), and spurious wakeups return
    with the predicate still false.  ``Event.wait`` is level-triggered
    and exempt."""
    findings: List[Finding] = []
    for m in tm.modules.values():
        for fd in set(m.funcs.values()):
            for node in _walk_own(fd.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "wait"):
                    continue
                recv = node.func.value
                ld = None
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self" and fd.cls:
                    ld = m.lock(fd.cls, recv.attr)
                elif isinstance(recv, ast.Name):
                    ld = m.lock(None, recv.id)
                if ld is None or ld.kind != "condition":
                    continue
                if _enclosing_while(m, node):
                    continue
                findings.append(Finding(
                    "condvar-wait-no-predicate", m.rel,
                    f"Condition {ld.lock_id}.wait() outside a "
                    f"while-predicate loop in {fd.qualname} — a "
                    f"notify landing before the wait (or a spurious "
                    f"wakeup) is a lost wakeup; use `while not "
                    f"<predicate>: cv.wait()`",
                    line=node.lineno,
                    key=f"wait@{fd.qualname}"))
    return findings


# --------------------------------------- rule: unguarded shared state

def _thread_body_funcs(tm: TreeModel, m: ModuleModel,
                       ts: ThreadStart) -> List[FuncDef]:
    """The thread target plus the same-class methods it (transitively)
    calls — the code that runs concurrently with public callers."""
    entry: Optional[FuncDef] = None
    t = ts.target
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self" and ts.cls:
        entry = m.funcs.get(f"{ts.cls}.{t.attr}")
    elif isinstance(t, ast.Name):
        if ts.func:
            entry = m.funcs.get(f"{ts.func}.<locals>.{t.id}")
        if entry is None:
            entry = m.funcs.get(t.id)
    if entry is None:
        return []
    out, queue = [], [entry]
    seen = {entry.qualname}
    while queue:
        fd = queue.pop()
        out.append(fd)
        for node in _walk_own(fd.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" and ts.cls:
                callee = m.funcs.get(f"{ts.cls}.{node.func.attr}")
                if callee is not None \
                        and callee.qualname not in seen:
                    seen.add(callee.qualname)
                    queue.append(callee)
    return out


def _written_attrs(fds: List[FuncDef]) -> Dict[str, int]:
    """Instance attributes a thread body writes non-trivially.
    Constant assignments (``self.done = True``) are exempt: a
    single-word flag publish is exactly what the flag-based shutdown
    protocol prescribes — it is the read-modify-writes and container
    mutations that race."""
    out: Dict[str, int] = {}

    def _note(attr: str, line: int) -> None:
        out.setdefault(attr, line)

    for fd in fds:
        for node in _walk_own(fd.node):
            if isinstance(node, ast.Assign):
                targets: List[ast.AST] = []
                for t in node.targets:
                    targets.extend(t.elts if isinstance(
                        t, (ast.Tuple, ast.List)) else [t])
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" \
                            and not isinstance(node.value,
                                               ast.Constant):
                        _note(t.attr, node.lineno)
            elif isinstance(node, ast.AugAssign):
                t = node.target
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    _note(t.attr, node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                recv = node.func.value
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self":
                    _note(recv.attr, node.lineno)
    return out


_PUBLIC_DUNDERS = {"__call__", "__enter__", "__exit__", "__iter__",
                   "__next__", "__len__", "__getitem__"}


def check_unguarded_shared_state(tm: TreeModel) -> List[Finding]:
    """[unguarded-shared-state] instance attributes written inside a
    thread-target body (assignments of non-constants, augmented
    assigns, container mutators) that a PUBLIC method reads or writes
    without holding one of the instance's locks.  Flag publishes
    (constant assigns) are exempt — they are the sanctioned lock-free
    protocol.  Classes with no lock at all still flag: the fix is to
    add one (or pragma the site with why the access is safe)."""
    findings: List[Finding] = []
    for m in tm.modules.values():
        for ts in m.threads:
            if ts.cls is None:
                continue
            body = _thread_body_funcs(tm, m, ts)
            if not body:
                continue
            written = _written_attrs(body)
            if not written:
                continue
            body_names = {fd.qualname for fd in body}
            cls_locks = [ld for (c, _), ld in m.sync.items()
                         if c == ts.cls and ld.is_lock]
            for fd in set(m.funcs.values()):
                if fd.cls != ts.cls or fd.qualname in body_names:
                    continue
                name = fd.node.name
                if name.startswith("_") and name not in _PUBLIC_DUNDERS:
                    continue
                flagged: Set[str] = set()
                for node in _walk_own(fd.node):
                    if not (isinstance(node, ast.Attribute)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"
                            and node.attr in written):
                        continue
                    if node.attr in flagged:
                        continue
                    if _held_lock(tm, m, node, fd.cls) is not None:
                        continue
                    flagged.add(node.attr)
                    lock_hint = (cls_locks[0].lock_id if cls_locks
                                 else f"{ts.cls} has no lock — add "
                                      f"one")
                    findings.append(Finding(
                        "unguarded-shared-state", m.rel,
                        f"{ts.cls}.{name} touches self.{node.attr} "
                        f"without a lock, but the {ts.cls} thread "
                        f"body writes it concurrently "
                        f"(hold {lock_hint})",
                        line=node.lineno,
                        key=f"{ts.cls}.{name}:{node.attr}"))
    return findings


# -------------------------------------------- rule: blocking under lock

def _blocking_label(tm: TreeModel, m: ModuleModel, call: ast.Call,
                    cls: Optional[str],
                    local_threads: Set[str]) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in _BLOCKING_NAMES:
            return f"{f.id}()"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    if isinstance(f.value, ast.Name):
        q = (f.value.id, f.attr)
        if q in _BLOCKING_QUALIFIED:
            return f"{q[0]}.{q[1]}()"
    if f.attr == "join":
        # thread joins only — str.join is everywhere and harmless
        recv = f.value
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" \
                and (cls, recv.attr) in m.thread_attrs:
            return f"self.{recv.attr}.join()"
        if isinstance(recv, ast.Name) and recv.id in local_threads:
            return f"{recv.id}.join()"
        return None
    if f.attr in _BLOCKING_ATTRS:
        return f"{_call_label(call)}()"
    return None


def check_blocking_under_lock(tm: TreeModel) -> List[Finding]:
    """[blocking-under-lock] device round trips, file/process I/O,
    sleeps, ``Future.result()``, thread joins, and event-bus emits
    reachable (directly, or one resolvable call deep) while a lock is
    held — every other would-be holder serializes behind the wait,
    which is the runtime stall class the Heartbeat watchdog exists to
    catch.  Deliberate holds (e.g. a per-line JSONL write whose lock
    IS the line serializer) pragma with the why."""
    findings: List[Finding] = []
    for m in tm.modules.values():
        for fd in set(m.funcs.values()):
            # thread names are FUNCTION-local: another function's
            # `t = Thread(...)` must not make this function's
            # unrelated `t.join()` a thread join
            local_threads = {
                ts.store[1] for ts in m.threads
                if ts.store and ts.store[0] == "name"
                and ts.func == fd.qualname}
            for lid, wnode in tm.direct_acquires(fd):
                for node in _walk_body(wnode):
                    if not isinstance(node, ast.Call):
                        continue
                    label = _blocking_label(tm, m, node, fd.cls,
                                            local_threads)
                    via = ""
                    if label is None:
                        callee = tm.resolve_call(m, node, fd.cls)
                        if callee is None:
                            continue
                        cm = tm.modules[callee.module]
                        for cn in _walk_own(callee.node):
                            if isinstance(cn, ast.Call):
                                inner = _blocking_label(
                                    tm, cm, cn, callee.cls, set())
                                if inner is not None:
                                    label = inner
                                    via = f" via {callee.qualname}"
                                    break
                        if label is None:
                            continue
                    findings.append(Finding(
                        "blocking-under-lock", m.rel,
                        f"{label}{via} while holding {lid} in "
                        f"{fd.qualname} — blocks every other holder "
                        f"(move the slow call outside the lock, or "
                        f"pragma with why the hold is bounded)",
                        line=node.lineno,
                        key=f"{fd.qualname}:{label}{via}"))
    return findings


# ------------------------------------------ rule: thread shutdown path

def check_thread_shutdown(tm: TreeModel) -> List[Finding]:
    """[thread-no-shutdown-path] a started thread needs a bounded stop
    path: either some code joins it (``<store>.join(...)``) or its
    body polls a stop/cancel ``Event`` that some other code sets.
    ``daemon=True`` alone does not count — a daemon thread holding a
    lock shared with atexit/flight-recorder dumps deadlocks the
    teardown it was supposed to never block."""
    findings: List[Finding] = []
    for m in tm.modules.values():
        # events set anywhere in the module: name / self-attr
        set_calls: Set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "set":
                recv = node.func.value
                if isinstance(recv, ast.Name):
                    set_calls.add(recv.id)
                elif isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self":
                    set_calls.add(recv.attr)
        # a join on a LOCAL name only covers threads stored to that
        # name in the SAME function (two functions reusing `t` must
        # not vouch for each other); self-attr joins cover the SAME
        # class — close()/joining another method is the normal shape,
        # but one class's join must not vouch for another class's
        # same-named thread attr
        joined: Set[Tuple[str, str, Optional[str]]] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                recv = node.func.value
                if isinstance(recv, ast.Name):
                    joined.add(("name", recv.id,
                                _enclosing_func_qualname(m, node)))
                elif isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self":
                    joined.add(("attr", recv.attr,
                                _enclosing_class(m, node)))
        for ts in m.threads:
            if ts.store is not None:
                kind, ident = ts.store
                scope = ts.func if kind == "name" else ts.cls
                if (kind, ident, scope) in joined:
                    continue
            body = _thread_body_funcs(tm, m, ts)
            polls_stop = False
            for fd in body:
                for node in _walk_own(fd.node):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr in ("is_set", "wait"):
                        recv = node.func.value
                        nm = None
                        if isinstance(recv, ast.Name):
                            nm = recv.id
                        elif isinstance(recv, ast.Attribute) \
                                and isinstance(recv.value, ast.Name) \
                                and recv.value.id == "self":
                            nm = recv.attr
                        if nm is not None and nm in set_calls:
                            polls_stop = True
            if polls_stop:
                continue
            tname = (_const_target_name(ts) or "<unresolved>")
            findings.append(Finding(
                "thread-no-shutdown-path", m.rel,
                f"thread target {tname} started"
                + (f" in {ts.func}" if ts.func else "")
                + " with no bounded stop path: nothing joins it and "
                  "its body polls no stop Event (daemon= alone does "
                  "not count for threads sharing locks with "
                  "atexit/flight-recorder paths)",
                line=ts.node.lineno,
                key=f"thread={ts.func or m.rel}:{tname}"))
    return findings


def _const_target_name(ts: ThreadStart) -> Optional[str]:
    t = ts.target
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute):
        return ast.unparse(t) if hasattr(ast, "unparse") else t.attr
    return None


# ----------------------------------- rule: artifact lock ownership

# On-disk artifacts shared ACROSS PROCESSES (the multi-process half
# of this level, ISSUE 14 satellite): the checkpoint-rotation prefix
# (N training processes, one rotation dir — the DCN drill's shared
# rotation), the persistent compile-cache dir (prewarm children +
# bench probes + serve replicas), and the prewarm warm-state JSON.
# Each has ONE sanctioned ownership protocol:
#
# - rotation prefix: the shared-rotation handshake — process 0 writes,
#   everyone else returns (utils/checkpoint.checkpoint_trainer's
#   ``jax.process_index() != 0`` gate), or a per-process prefix;
# - compile cache: jax's cache is multi-writer-safe by design
#   (content-addressed entries) — surfaced, never flagged;
# - warm state: atomic tmp + ``os.replace`` publish inside
#   write_warm_state — surfaced, never flagged.
#
# The rule: a rotation WRITE site (``<rotation>.save(...)``,
# ``checkpoint_trainer(...)``, ``save_checkpoint(...)``) with no
# process-ownership evidence anywhere on its call chain is a finding
# — two training processes pruning one rotation prefix unhandshaked
# corrupt each other's keep-window exactly like two threads on one
# unguarded list.

_ROTATION_CTOR = "CheckpointRotation"
_ROTATION_WRITERS = {"checkpoint_trainer", "save_checkpoint"}
_PER_PROCESS_PATH_MARKERS = ("getpid", "process_index", "pid")
_GATE_ATTRS = {"process_index", "process_count"}
# checkpoint-v3 two-phase-commit vocabulary: migrated to
# protocol_specs (roc-lint level eight owns the commit-ORDER rule,
# ``ckpt-commit-order``); the artifact surface below still inventories
# the same call sites through the shared helper so ``--select
# concurrency`` output stays stable.


def _refs_process_gate(tm: TreeModel, fd: FuncDef, _depth: int = 0,
                       _stack: Optional[Set[Tuple[str, str]]] = None
                       ) -> bool:
    """True when ``fd`` (or a resolvable callee within depth 4)
    consults the process identity — the shared-rotation handshake's
    signature."""
    if _stack is None:
        _stack = set()
    if fd.key in _stack or _depth > 4:
        return False
    _stack.add(fd.key)
    mod = tm.modules[fd.module]
    try:
        for node in _walk_own(fd.node):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _GATE_ATTRS:
                return True
            if isinstance(node, ast.Name) and node.id in _GATE_ATTRS:
                return True
            if isinstance(node, ast.Call):
                callee = tm.resolve_call(mod, node, fd.cls)
                if callee is not None and _refs_process_gate(
                        tm, callee, _depth + 1, _stack):
                    return True
        return False
    finally:
        _stack.discard(fd.key)


def _rotation_assigns(nodes: Iterable[ast.AST],
                      m: Optional[ModuleModel] = None
                      ) -> Dict[Tuple[str, str], str]:
    """``('name'|'attr', identifier) -> prefix source`` for every
    ``X = CheckpointRotation(<prefix>, ...)`` assignment among
    ``nodes``.  With ``m``, self-attr identifiers are qualified by
    their enclosing CLASS (``Cls.attr``) — two classes reusing one
    attribute name must never vouch for each other's prefixes."""
    out: Dict[Tuple[str, str], str] = {}
    for node in nodes:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        f = node.value.func
        ctor = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None)
        if ctor != _ROTATION_CTOR:
            continue
        prefix = ""
        if node.value.args:
            try:
                prefix = ast.unparse(node.value.args[0])
            except Exception:  # noqa: BLE001 - py<3.9 has no unparse
                prefix = "?"
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[("name", tgt.id)] = prefix
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                cls = _enclosing_class(m, node) if m else None
                out[("attr", f"{cls or ''}.{tgt.attr}")] = prefix
    return out


def _module_rotation_bindings(m: ModuleModel
                              ) -> Dict[Tuple[str, str], str]:
    """The module-wide rotation bindings: self-attr bindings
    (class-scoped keys) plus module-LEVEL name bindings.  Name
    assignments inside functions are deliberately excluded — their
    values must never shadow a module-level binding of the same name
    (a function-local per-process prefix would otherwise vouch for
    an unrelated module-level shared-prefix writer)."""
    out = {k: v
           for k, v in _rotation_assigns(ast.walk(m.tree), m).items()
           if k[0] == "attr"}
    out.update(
        {k: v for k, v in _rotation_assigns(
            (n for n in ast.iter_child_nodes(m.tree)), m).items()
         if k[0] == "name"})
    return out


def _rotation_bindings(m: ModuleModel,
                       fd: Optional[FuncDef] = None,
                       base: Optional[Dict[Tuple[str, str], str]]
                       = None) -> Dict[Tuple[str, str], str]:
    """Rotation bindings visible to ``fd``: name-bindings are
    FUNCTION-scoped (two functions reusing ``rot`` must not vouch
    for each other's prefixes — the per-process exemption of one
    must never leak onto the other), self-attr bindings are
    class-scoped, module-level names module-wide.  ``base`` lets a
    caller hoist :func:`_module_rotation_bindings` out of a per-
    function loop."""
    out = dict(base if base is not None
               else _module_rotation_bindings(m))
    if fd is not None:
        out.update(_rotation_assigns(
            (n for n in _walk_own(fd.node)
             if isinstance(n, ast.Assign)), m))
    return out


def _rotation_save_gated(tm: TreeModel) -> bool:
    """Whether the tree's own ``CheckpointRotation.save`` carries the
    handshake (transitively) — then every ``<rotation>.save(...)``
    call site inherits the evidence.  False when the class is not in
    the tree (fixture trees importing it from elsewhere must carry
    their own gate)."""
    for fd in tm.methods_by_name.get("save", []):
        if fd.cls == _ROTATION_CTOR and _refs_process_gate(tm, fd):
            return True
    return False


def check_artifact_lock_ownership(tm: TreeModel) -> List[Finding]:
    """[artifact-lock-ownership] see the section comment above.
    Ownership evidence, any one of which clears a write site: the
    process-identity gate on the enclosing function or anywhere down
    the written-through call chain; a per-process prefix
    (pid/process_index in the path expression); or the standard
    pragma documenting why single-writer is guaranteed."""
    findings: List[Finding] = []
    rot_gated = _rotation_save_gated(tm)
    for m in tm.modules.values():
        base = _module_rotation_bindings(m)
        for fd in set(m.funcs.values()):
            bindings = _rotation_bindings(m, fd, base=base)
            for node in _walk_own(fd.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                label = prefix = None
                chain_gated = False
                if isinstance(f, ast.Attribute) and f.attr == "save":
                    recv = f.value
                    key = None
                    if isinstance(recv, ast.Name):
                        key = ("name", recv.id)
                        # convention fallback: a parameter named
                        # rotation* IS a CheckpointRotation (the
                        # train_with_recovery shape)
                        if key not in bindings \
                                and not recv.id.startswith("rotation"):
                            key = None
                    elif isinstance(recv, ast.Attribute) \
                            and isinstance(recv.value, ast.Name) \
                            and recv.value.id == "self":
                        key = ("attr", f"{fd.cls or ''}.{recv.attr}")
                        if key not in bindings:
                            key = None
                    if key is None:
                        continue
                    label = f"{key[1]}.save()"
                    prefix = bindings.get(key, "")
                    chain_gated = rot_gated
                elif isinstance(f, ast.Name) \
                        and f.id in _ROTATION_WRITERS:
                    label = f"{f.id}()"
                    callee = tm.resolve_call(m, node, fd.cls)
                    chain_gated = (callee is not None
                                   and _refs_process_gate(tm, callee))
                else:
                    continue
                if chain_gated or _refs_process_gate(tm, fd):
                    continue
                if prefix and any(mk in prefix for mk in
                                  _PER_PROCESS_PATH_MARKERS):
                    continue
                findings.append(Finding(
                    "artifact-lock-ownership", m.rel,
                    f"{label} in {fd.qualname} writes a checkpoint-"
                    f"rotation prefix"
                    + (f" ({prefix})" if prefix else "")
                    + " with no process-ownership evidence: under "
                      "multi-process SPMD every process would write "
                      "and prune the same rotation — gate on "
                      "jax.process_index() (the shared-rotation "
                      "handshake) or use a per-process prefix",
                    line=node.lineno,
                    key=f"writer|{fd.qualname}|{label}"))
    return findings


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def artifact_surface(tm: TreeModel) -> List[Dict[str, Any]]:
    """Per-module artifact-lock inventory for the surface table:
    which process-shared on-disk artifacts each module touches
    (rotation prefixes with their ownership evidence, compile-cache
    enables, warm-state publishes)."""
    rot_gated = _rotation_save_gated(tm)
    out: List[Dict[str, Any]] = []
    for rel in sorted(tm.modules):
        m = tm.modules[rel]
        arts: List[Dict[str, Any]] = []
        for (kind, name), prefix in sorted(
                _rotation_assigns(ast.walk(m.tree), m).items()):
            arts.append({"kind": "rotation",
                         "name": name.lstrip("."),
                         "path": prefix,
                         "owner": ("proc0-gate" if rot_gated
                                   else "unknown")})
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            callee = (f.id if isinstance(f, ast.Name)
                      else f.attr if isinstance(f, ast.Attribute)
                      else None)
            if callee == "enable_compile_cache":
                arts.append({"kind": "compile-cache",
                             "line": node.lineno,
                             "owner": "multi-writer-safe"})
            elif callee == "write_warm_state":
                arts.append({"kind": "warm-state",
                             "line": node.lineno,
                             "owner": "atomic-replace"})
        # checkpoint-v3 shard/manifest call sites: the writer
        # vocabulary and inventory live in protocol_specs (level
        # eight is the one source of truth; this surface keeps them
        # for ``--select concurrency`` output stability)
        arts.extend(ckpt_artifact_entries(m.tree))
        if arts:
            out.append({"module": rel, "artifacts": arts})
    return out


# ------------------------------------------------- surface + entrypoint

def concurrency_surface(tm: TreeModel) -> Dict[str, Any]:
    """The discovered thread model, per module — threads (target,
    daemon, stop path), locks (owner.attr, kind), handlers — the
    payload behind ``--json``'s ``concurrency_surface`` and the
    ``roc_tpu.report`` table.  The audit doubles as documentation: if
    a thread or lock is missing here, the auditor (and therefore every
    rule above) cannot see it."""
    mods: List[Dict[str, Any]] = []
    for rel in sorted(tm.modules):
        m = tm.modules[rel]
        if not (m.threads or m.sync or m.handlers):
            continue
        threads = []
        for ts in m.threads:
            threads.append({
                "target": _const_target_name(ts),
                "in": ts.func, "daemon": ts.daemon,
                "name": ts.name, "line": ts.node.lineno})
        locks = [{"name": (f"{c}.{n}" if c else n), "kind": ld.kind,
                  "line": ld.line}
                 for (c, n), ld in sorted(
                     m.sync.items(),
                     key=lambda kv: (kv[0][0] or "", kv[0][1]))]
        handlers = []
        for reg in m.handlers:
            h = reg.handler
            label = None
            if isinstance(h, ast.Attribute):
                if h.attr in ("SIG_DFL", "SIG_IGN"):
                    continue    # disposition reset, not a handler
                label = h.attr
            elif isinstance(h, ast.Name):
                label = h.id
            handlers.append({"handler": label,
                             "line": reg.node.lineno})
        mods.append({"module": rel, "threads": threads,
                     "locks": locks, "handlers": handlers})
    artifacts = artifact_surface(tm)
    return {
        "modules": mods,
        "artifacts": artifacts,
        "totals": {
            "modules": len(mods),
            "threads": sum(len(x["threads"]) for x in mods),
            "locks": sum(len(x["locks"]) for x in mods),
            "handlers": sum(len(x["handlers"]) for x in mods),
            "artifacts": sum(len(x["artifacts"])
                             for x in artifacts)}}


_CHECKS = {
    "signal-unsafe-handler": check_signal_handlers,
    "lock-order-cycle": check_lock_order,
    "condvar-wait-no-predicate": check_condvar_predicates,
    "unguarded-shared-state": check_unguarded_shared_state,
    "blocking-under-lock": check_blocking_under_lock,
    "thread-no-shutdown-path": check_thread_shutdown,
    "artifact-lock-ownership": check_artifact_lock_ownership,
}


def run_concurrency_lint(root: str,
                         select: Optional[List[str]] = None,
                         tree_model: Optional[TreeModel] = None
                         ) -> List[Finding]:
    """Run the selected (default: all) concurrency rules over
    ``root``.  Pure AST — no jax, milliseconds.  Per-line pragma
    suppression applies to every finding with a line; the
    cross-module ``lock-order-cycle`` rule checks its pragmas at each
    participating acquisition site itself."""
    tm = tree_model if tree_model is not None else TreeModel(root)
    findings: List[Finding] = []
    for name, check in _CHECKS.items():
        if select is not None and name not in select:
            continue
        for f in check(tm):
            m = tm.modules.get(f.unit)
            if m is not None and pragma_ok(m.lines, f.line, f.rule):
                continue
            findings.append(f)
    return findings


def audit_concurrency(root: str,
                      select: Optional[List[str]] = None,
                      extras: Optional[Dict[str, Any]] = None
                      ) -> List[Finding]:
    """Level-six entry point for the driver: run the rules, stash the
    surface under ``extras['concurrency']``, and emit the surface as
    an ``analysis`` event (kind=``concurrency_surface``) so a run
    artifact documents its own thread model and
    ``python -m roc_tpu.report`` can render the table from the event
    stream alone."""
    from ..obs.events import emit
    tm = TreeModel(root)
    findings = run_concurrency_lint(root, select=select,
                                    tree_model=tm)
    surface = concurrency_surface(tm)
    if extras is not None:
        extras["concurrency"] = surface
    t = surface["totals"]
    emit("analysis",
         f"concurrency surface: {t['threads']} thread(s), "
         f"{t['locks']} sync object(s), {t['handlers']} signal "
         f"handler(s) across {t['modules']} module(s)",
         console=False, kind="concurrency_surface",
         modules=surface["modules"], totals=t)
    return findings
