"""Bounded explicit-state model checker — roc-lint level eight's
exhaustive half.

Small-scope model checking in the TLA+/Alloy spirit, applied to the
three distributed protocols this repo actually ships: every model is a
hand-derived abstraction of the code (the extraction side of
:mod:`protocol_lint` pins the code's transition sites; the declared
invariants live in :mod:`protocol_specs`), explored by exhaustive BFS
over *every* interleaving and crash-at-any-step schedule within a hard
state budget.  Pure Python, jax-free, deterministic — milliseconds, so
it rides the same preflight as the AST levels.

The three models:

- **router-lifecycle** (``serve/router.py``): one admitted request,
  two replicas that can crash at any step, retryable failures bounded
  by ``max_tries``, failover requeue guarded by the per-corpse
  ``rep.requeued`` flag, the monitor's deadline backstop, and
  ``close()``.  Invariants: a request completes at most once; a dead
  replica's orphans are requeued at most once per corpse; no
  completion lands after ``ServeClosed``; every reachable state has a
  path to a terminal (the deadline makes "never a hang" a theorem of
  the model, not a hope).
- **ckpt-commit** (``utils/checkpoint.py``): the v3 two-phase commit
  with two writer processes — un-commit (manifest removal) first,
  per-process shard renames, barrier, manifest publish last — with a
  whole-job crash allowed between any two operations.  Invariants:
  the manifest is only ever present when every shard it references
  has landed (publish-last), and restore never selects torn state
  from any crash point.
- **table-swap** (``serve/server.py``): one microbatch racing a
  versioned-table publish.  The dispatcher captures ``published()``
  ONCE per microbatch; the invariant is that every row of the batch
  is served from exactly that one version, under any interleaving of
  the swap.  PR 20 adds the sharded-serving gather leg: the batch's
  second row is FOREIGN (owned by another shard) and must be fetched
  from the owner — the correct protocol pins the fetch to the
  captured version (a mismatched answer is re-gathered, never
  served), so a mid-rollout gather can't stage rows from a version
  the batch didn't capture.

Each model carries seedable bugs (``seed=`` names one) so the test
tier can prove the checker actually bites: ``double-requeue`` drops
the per-corpse requeue guard, ``manifest-first`` publishes the
manifest before the shard renames, ``swap-mid-query`` reads the live
published version per row instead of the captured one,
``live-qmode`` (PR 19) keeps the captured rows but picks the dequant
program from the live published version's quant spec — the
mid-rollout fp32→int8 window ``quant-spec-pinned`` exists for — and
``shard-gather`` (PR 20) drops the gather's version pin and serves
whatever the owner's live table answered, the cross-shard
version-mixing window ``gather-version-pinned`` exists for.
"""

from __future__ import annotations

from collections import deque, namedtuple
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# hard per-model cap on distinct states: the preflight contract is
# milliseconds, so exploration that would exceed this aborts with
# ``complete=False`` — which protocol_lint turns into a finding (an
# unexplorable model is a broken tripwire, not a pass).  The three
# shipped models explore well under 10k states combined.
STATE_BUDGET = 20_000

MODELS = ("router-lifecycle", "ckpt-commit", "table-swap")

# the canonical seedable bug per model (test fixtures)
SEEDS = {
    "router-lifecycle": "double-requeue",
    "ckpt-commit": "manifest-first",
    "table-swap": "swap-mid-query",
}

# additional seedable bugs (PR 19): the quantized-rollout window —
# "live-qmode" keeps the captured version's ROWS but selects the
# dequant program by the LIVE published version's quant spec, the
# mid-rollout bug class quant-spec-pinned exists to catch
EXTRA_SEEDS = {
    # "shard-gather" (PR 20): the cross-shard gather serves whatever
    # version the owner's live table answered instead of refusing a
    # version != the microbatch's capture — the mixing window
    # gather-version-pinned exists to catch
    "table-swap": ("live-qmode", "shard-gather"),
}


@dataclass
class ModelReport:
    """One model's exploration verdict."""
    name: str
    invariants: Tuple[str, ...]
    states: int = 0
    transitions: int = 0
    complete: bool = True
    violations: List[Dict[str, Any]] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"model": self.name,
                "invariants": list(self.invariants),
                "states": self.states,
                "transitions": self.transitions,
                "complete": self.complete,
                "violations": self.violations}


def _trace(seen: Dict[Any, Any], state: Any) -> List[str]:
    """Action labels from the initial state to ``state`` (the BFS
    predecessor chain — a shortest counterexample schedule)."""
    labels: List[str] = []
    while seen[state] is not None:
        prev, label = seen[state]
        labels.append(label)
        state = prev
    return list(reversed(labels))


def _bfs(name: str,
         init: Any,
         step: Callable[[Any], List[Tuple[str, Any]]],
         invariants: List[Tuple[str, Callable[[Any], Optional[str]]]],
         liveness: Optional[Tuple[str, Callable[[Any], bool]]] = None,
         budget: int = STATE_BUDGET) -> ModelReport:
    """Exhaustive BFS from ``init``.  ``step`` returns the enabled
    transitions of a state (label, successor); ``invariants`` are
    state predicates returning a violation message or None;
    ``liveness`` (name, terminal_ok) flags any deadlocked state that
    is not a sanctioned terminal.  First violation per invariant is
    reported with its counterexample trace; exploration continues so
    one broken invariant cannot mask another."""
    names = tuple(n for n, _ in invariants) + (
        (liveness[0],) if liveness else ())
    rep = ModelReport(name=name, invariants=names)
    seen: Dict[Any, Any] = {init: None}
    frontier = deque([init])
    tripped: set = set()

    def check(state: Any) -> None:
        for inv_name, fn in invariants:
            if inv_name in tripped:
                continue
            msg = fn(state)
            if msg:
                tripped.add(inv_name)
                rep.violations.append({
                    "invariant": inv_name, "msg": msg,
                    "trace": _trace(seen, state)})

    check(init)
    while frontier:
        state = frontier.popleft()
        succ = step(state)
        if not succ and liveness and liveness[0] not in tripped \
                and not liveness[1](state):
            tripped.add(liveness[0])
            rep.violations.append({
                "invariant": liveness[0],
                "msg": "deadlock: state has no enabled transition "
                       "and is not a sanctioned terminal",
                "trace": _trace(seen, state)})
        for label, nxt in succ:
            rep.transitions += 1
            if nxt in seen:
                continue
            if len(seen) >= budget:
                rep.complete = False
                rep.states = len(seen)
                return rep
            seen[nxt] = (state, label)
            check(nxt)
            frontier.append(nxt)
    rep.states = len(seen)
    return rep


def _set(tup: tuple, i: int, v: Any) -> tuple:
    out = list(tup)
    out[i] = v
    return tuple(out)


# ------------------------------------------------ model 1: router

# owners: frozenset of replica ids the request is in flight on
# crashed/orphan: per-replica flags (orphan = "owned the request when
#   it crashed" — what _mark_dead's pending scan sees)
# observed: per-replica count of _mark_dead entries processed (the
#   reader-EOF and monitor-poll paths can BOTH get there; the
#   rep.requeued guard makes the second a no-op)
# requeues: per-replica failover-requeue count for the invariant
_R = namedtuple("_R", "owners crashed orphan observed requeues tries "
                      "closed terminal completions")

_MAX_TRIES = 2
_N_REPLICAS = 2


def _router_step(seed: Optional[str]
                 ) -> Callable[[Any], List[Tuple[str, Any]]]:
    seeded = seed == "double-requeue"
    # without the guard, _mark_dead can be fully processed twice per
    # corpse (reader EOF + monitor poll racing before the requeue
    # updates sub.replica) — each pass requeues the orphans again
    max_observe = 2 if seeded else 1

    def step(s: _R) -> List[Tuple[str, Any]]:
        out: List[Tuple[str, Any]] = []
        if s.terminal is not None:
            return out      # terminal states are frozen
        # monitor deadline: authoritative and replica-independent —
        # enabled in EVERY non-terminal state (the liveness witness)
        out.append(("deadline", s._replace(
            terminal="timeout", owners=frozenset())))
        if not s.closed:
            # close() pops pending and fails typed ServeClosed; a
            # late result for a popped sub is dropped (sub is None)
            out.append(("close", s._replace(
                closed=True, terminal="closed", owners=frozenset())))
        for r in sorted(s.owners):
            if s.crashed[r]:
                continue
            # replica answers ok → _on_result pops pending, completes
            out.append((f"ok@{r}", s._replace(
                terminal="ok", owners=frozenset(),
                completions=s.completions + 1)))
            # replica answers a retryable failure → re-dispatch,
            # bounded by max_tries
            if s.tries < _MAX_TRIES:
                targets = [t for t in range(_N_REPLICAS)
                           if not s.crashed[t]]
                for t in targets:
                    out.append((f"retry@{r}->{t}", s._replace(
                        owners=frozenset({t}), tries=s.tries + 1)))
            else:
                out.append((f"fail@{r}", s._replace(
                    terminal="error", owners=frozenset())))
        for r in range(_N_REPLICAS):
            if not s.crashed[r]:
                # the replica_sigkill drill: crash at any step
                out.append((f"crash@{r}", s._replace(
                    crashed=_set(s.crashed, r, True),
                    orphan=_set(s.orphan, r, r in s.owners))))
            elif s.observed[r] < max_observe:
                # _mark_dead (reader EOF or monitor poll)
                ns = s._replace(
                    observed=_set(s.observed, r, s.observed[r] + 1))
                requeue = s.orphan[r] and (seeded
                                           or s.requeues[r] == 0)
                if not requeue:
                    out.append((f"markdead@{r}", ns._replace(
                        owners=s.owners - {r})))
                    continue
                nreq = _set(s.requeues, r, s.requeues[r] + 1)
                survivors = [t for t in range(_N_REPLICAS)
                             if not s.crashed[t]]
                if not survivors:
                    out.append((f"markdead@{r}-lost", ns._replace(
                        owners=frozenset(), requeues=nreq,
                        terminal="lost")))
                else:
                    for t in survivors:
                        out.append((
                            f"markdead@{r}-requeue@{t}",
                            ns._replace(
                                owners=(s.owners - {r}) | {t},
                                requeues=nreq)))
        return out

    return step


def _router_model(seed: Optional[str], budget: int) -> ModelReport:
    init = _R(owners=frozenset({0}),
              crashed=(False,) * _N_REPLICAS,
              orphan=(False,) * _N_REPLICAS,
              observed=(0,) * _N_REPLICAS,
              requeues=(0,) * _N_REPLICAS,
              tries=1, closed=False, terminal=None, completions=0)
    invariants = [
        ("terminal-exactly-once", lambda s: (
            None if s.completions <= 1 else
            f"request completed {s.completions} times — a late/"
            f"duplicate result overwrote a terminal state")),
        ("failover-requeue-at-most-once", lambda s: (
            None if max(s.requeues) <= 1 else
            f"corpse requeued {max(s.requeues)} times — duplicate "
            f"_mark_dead passes re-dispatched the same orphans "
            f"(the rep.requeued guard)")),
        ("no-completion-after-close", lambda s: (
            None if not (s.closed and s.completions > 0) else
            "a result completed a request after ServeClosed — "
            "close() must pop pending first")),
    ]
    return _bfs("router-lifecycle", init, _router_step(seed),
                invariants,
                liveness=("deadline-liveness",
                          lambda s: s.terminal is not None),
                budget=budget)


# ------------------------------------------- model 2: ckpt commit

# two writer processes over a pre-existing COMMITTED old checkpoint
# (the replayed-epoch rewrite — the hardest case):
#   proc0: uncommit → [barrier] → replace shard0 → [barrier] → commit
#   proc1:            [barrier] → replace shard1 → [barrier]
# The PRE barrier is the fix this model forced on landing: without
# it, proc1's replace races proc0's un-commit and a crash in that
# window leaves the old manifest live over a half-replaced shard
# set.  Shards/manifest record the generation on disk; crash freezes
# the whole job at any point (the SIGKILL-in-commit drill).
_C = namedtuple("_C", "pc0 pc1 shards manifest crashed")

_OPS0 = ("uncommit", "barrier-pre", "replace0", "barrier-commit",
         "commit")
# the seeded bug publishes the manifest before its shard rename
_OPS0_SEEDED = ("uncommit", "barrier-pre", "commit", "replace0",
                "barrier-commit")
_OPS1 = ("barrier-pre", "replace1", "barrier-commit")


def _ckpt_apply(s: _C, op: str) -> _C:
    if op == "uncommit":
        return s._replace(manifest="absent")
    if op == "replace0":
        return s._replace(shards=_set(s.shards, 0, "new"))
    if op == "replace1":
        return s._replace(shards=_set(s.shards, 1, "new"))
    if op == "commit":
        return s._replace(manifest="new")
    return s    # barrier mutates nothing on disk


def _ckpt_step(seed: Optional[str]
               ) -> Callable[[Any], List[Tuple[str, Any]]]:
    ops0 = _OPS0_SEEDED if seed == "manifest-first" else _OPS0

    def step(s: _C) -> List[Tuple[str, Any]]:
        out: List[Tuple[str, Any]] = []
        if s.crashed:
            return out
        done0, done1 = s.pc0 >= len(ops0), s.pc1 >= len(_OPS1)
        op0 = None if done0 else ops0[s.pc0]
        op1 = None if done1 else _OPS1[s.pc1]
        at_b0 = op0 is not None and op0.startswith("barrier")
        at_b1 = op1 is not None and op1.startswith("barrier")
        if at_b0 and at_b1 and op0 == op1:
            # the multi-writer barrier releases both procs together
            out.append((op0, s._replace(pc0=s.pc0 + 1,
                                        pc1=s.pc1 + 1)))
        else:
            if not done0 and not at_b0:
                out.append((f"p0:{op0}",
                            _ckpt_apply(s, op0)._replace(
                                pc0=s.pc0 + 1)))
            if not done1 and not at_b1:
                out.append((f"p1:{op1}",
                            _ckpt_apply(s, op1)._replace(
                                pc1=s.pc1 + 1)))
        if not (done0 and done1):
            # whole-job SIGKILL between any two operations
            out.append(("crash", s._replace(crashed=True)))
        return out

    return step


def _ckpt_torn(s: _C) -> Optional[str]:
    """Restore's verdict on the disk state: the manifest (when
    present) must reference a fully-landed generation.  ``old`` +
    any new shard is exactly the window the un-commit-first step
    closes; ``new`` + any old shard is the publish-last window."""
    if s.manifest == "absent":
        return None     # uncommitted dir: restore falls back, by design
    if any(sh != s.manifest for sh in s.shards):
        return (f"manifest '{s.manifest}' is live while shards are "
                f"{list(s.shards)} — restore would select torn state")
    return None


def _ckpt_model(seed: Optional[str], budget: int) -> ModelReport:
    init = _C(pc0=0, pc1=0, shards=("old", "old"), manifest="old",
              crashed=False)
    invariants = [
        ("manifest-published-last", lambda s: (
            None if not (s.manifest == "new"
                         and any(sh != "new" for sh in s.shards))
            else "manifest committed before every shard rename "
                 "landed — the commit record points at files that "
                 "may never exist")),
        ("restore-never-torn", _ckpt_torn),
    ]
    return _bfs("ckpt-commit", init, _ckpt_step(seed), invariants,
                budget=budget)


# -------------------------------------------- model 3: table swap

# one two-row microbatch racing one publish: the dispatcher captures
# published() once (step 0), then serves each row from the capture.
# PR 19: the publish is a QUANTIZED rollout — version 0 is fp32,
# version 1 int8 (_QMODE), so every served row records the (version,
# decode-mode) pair and quant-spec-pinned can distinguish "read the
# wrong version's rows" from "decoded the right rows with the wrong
# version's program".
# PR 20: row 1 is FOREIGN (owned by another shard) — serving it
# requires a gather first, which stages rows read from the owner's
# LIVE published table (``gathered`` records that version).  The
# correct protocol only serves the staged rows when the gathered
# version equals the capture (a mismatch is re-gathered); the
# shard-gather seed drops that pin.
_S = namedtuple("_S", "published captured gathered served step")

# the quant spec each published version carries (the mid-rollout
# fp32→int8 swap the serve tier's versioned publish protocol covers)
_QMODE = ("fp32", "int8")


def _swap_step(seed: Optional[str]
               ) -> Callable[[Any], List[Tuple[str, Any]]]:
    live_rows = seed == "swap-mid-query"
    live_mode = seed == "live-qmode"
    unpinned_gather = seed == "shard-gather"

    def step(s: _S) -> List[Tuple[str, Any]]:
        out: List[Tuple[str, Any]] = []
        done = s.step >= 3
        if s.published == 0 and not done:
            # add_edges / quantized rollout publishes v1 at any point
            out.append(("publish@v1:int8", s._replace(published=1)))
        if s.step == 0:
            out.append(("capture", s._replace(
                captured=s.published, step=1)))
        elif not done:
            row = s.step - 1
            # seeded bug 1 reads the LIVE published version's rows
            # instead of the microbatch's captured ones
            v = s.published if live_rows else s.captured
            # seeded bug 2 keeps the captured rows but selects the
            # dequant program by the LIVE version's quant spec
            m = _QMODE[s.published if live_mode else v]
            if row == 0:
                # row 0 is LOCAL: served straight from the capture
                out.append((f"serve_row{row}@v{v}:{m}", s._replace(
                    served=_set(s.served, row, (v, m)),
                    step=s.step + 1)))
            else:
                # row 1 is FOREIGN: a gather (re-gather) reads the
                # owner's live published table at any point...
                out.append((f"gather@v{s.published}", s._replace(
                    gathered=s.published)))
                # ...and the staged rows are served only once the
                # gathered version matches the pin — unless the
                # shard-gather seed dropped the pin check
                if s.gathered is not None and (
                        unpinned_gather or s.gathered == s.captured):
                    out.append((
                        f"serve_row{row}@v{v}:{m}"
                        f":staged@v{s.gathered}",
                        s._replace(served=_set(s.served, row, (v, m)),
                                   step=s.step + 1)))
        return out

    return step


def _swap_invariant(s: _S) -> Optional[str]:
    got = {v for v, _ in (x for x in s.served if x is not None)}
    if len(got) > 1 or (got and s.captured is not None
                        and got != {s.captured}):
        return (f"microbatch served rows from versions "
                f"{sorted(got)} (captured v{s.captured}) — every "
                f"microbatch must come from exactly one published "
                f"version")
    return None


def _swap_quant_invariant(s: _S) -> Optional[str]:
    for x in s.served:
        if x is None:
            continue
        v, m = x
        if m != _QMODE[v]:
            return (f"row read from v{v} ({_QMODE[v]} table) was "
                    f"decoded with the {m} program — the quant spec "
                    f"must travel WITH the captured version, not be "
                    f"re-read from the live publication mid-batch")
    return None


def _swap_gather_invariant(s: _S) -> Optional[str]:
    # checked once the FOREIGN row was served: ``gathered`` is frozen
    # after the serve (gathers are only offered before it), so it IS
    # the version the staged rows came from
    if s.served[1] is not None and s.gathered != s.captured:
        return (f"foreign row served from rows gathered at "
                f"v{s.gathered} into a batch that captured "
                f"v{s.captured} — a cross-shard gather must be "
                f"pinned to the captured version (mismatched answers "
                f"are re-gathered, never served)")
    return None


def _swap_model(seed: Optional[str], budget: int) -> ModelReport:
    init = _S(published=0, captured=None, gathered=None,
              served=(None, None), step=0)
    return _bfs("table-swap", init, _swap_step(seed),
                [("single-version-batch", _swap_invariant),
                 ("quant-spec-pinned", _swap_quant_invariant),
                 ("gather-version-pinned", _swap_gather_invariant)],
                budget=budget)


# ----------------------------------------------------- entry points

_BUILDERS = {
    "router-lifecycle": _router_model,
    "ckpt-commit": _ckpt_model,
    "table-swap": _swap_model,
}


def model_invariants() -> Dict[str, Tuple[str, ...]]:
    """Invariant names per model, AS IMPLEMENTED — cross-checked by
    protocol_lint against the declared protocol_specs tables (drift
    in either direction is a finding)."""
    out: Dict[str, Tuple[str, ...]] = {}
    for name in MODELS:
        out[name] = run_model(name, budget=1).invariants
    return out


def run_model(name: str, seed: Optional[str] = None,
              budget: int = STATE_BUDGET) -> ModelReport:
    """Explore one model exhaustively.  ``seed`` arms that model's
    known bug (:data:`SEEDS`) so the violation machinery can be
    regression-tested; unknown names raise."""
    if name not in _BUILDERS:
        raise ValueError(f"unknown model {name!r}; have {MODELS}")
    known = (SEEDS.get(name),) + EXTRA_SEEDS.get(name, ())
    if seed is not None and seed not in known:
        raise ValueError(f"unknown seed {seed!r} for {name!r}; "
                         f"have {known}")
    return _BUILDERS[name](seed, budget)


def check_all(budget: int = STATE_BUDGET) -> List[ModelReport]:
    """Explore all three models (un-seeded: the shipped protocol)."""
    return [run_model(name, budget=budget) for name in MODELS]
