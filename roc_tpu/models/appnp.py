"""APPNP model family: Approximate Personalized Propagation of Neural
Predictions (Gasteiger et al., ICLR'19).

``H = MLP(X); Z_0 = H; Z_{k+1} = (1 - alpha) * S Z_k + alpha * H``
with ``S = D^-1/2 A D^-1/2`` (self edges pre-added — the reference's
GCN normalization, ``gnn.cc:78-91``) and a FIXED teleport ``alpha``.
The reference has no such model; APPNP completes the zoo with the
decoupled predict-then-propagate family: all parameters live in the
MLP, so depth-k propagation adds NO weights and cannot oversmooth the
way a k-layer GCN does (the teleport keeps every hop anchored to the
prediction H).

On TPU the propagation is k ``scatter_gather`` ops through whatever
aggregation layout the trainer resolved (sectioned / bdense / ell —
the loop body is identical to GCN's hot path), combined per hop by
the builder's fixed-scalar ``lerp`` op — XLA fuses the lerp into the
aggregation output, so a hop costs the same as an SGC hop.

``layers`` follows the CLI convention: ``layers[0]`` input feature
dim, ``layers[-1]`` class count, intermediate entries are the MLP's
ReLU-separated hidden widths.
"""

from __future__ import annotations

from typing import Sequence

from .builder import Model
from ..ops.dense import AC_MODE_NONE


def build_appnp(layers: Sequence[int], k: int = 10,
                alpha: float = 0.1,
                dropout_rate: float = 0.5) -> Model:
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(f"alpha must be in [0, 1], got {alpha}")
    if k < 1:
        raise ValueError(
            f"k must be >= 1 (k=0 is a bare MLP with no propagation "
            f"— surely not what an APPNP user asked for), got {k}")
    model = Model(in_dim=layers[0])
    t = model.input()
    n = len(layers)
    for i in range(1, n):
        t = model.dropout(t, dropout_rate)
        t = model.linear(t, layers[i], AC_MODE_NONE)
        if i != n - 1:
            t = model.relu(t)
    h = t
    for _ in range(k):
        t = model.indegree_norm(t)
        t = model.scatter_gather(t)
        t = model.indegree_norm(t)
        t = model.lerp(t, h, alpha)
    model.softmax_cross_entropy(t)
    return model
