"""GCN model family: the reference driver's layer stack.

Reproduces ``top_level_task``'s model construction (``gnn.cc:75-92``): for
each layer spec entry after the first::

    t = dropout(t, rate)
    input = t
    t = linear(t, layers[i], AC_MODE_NONE)
    t = indegree_norm(t)
    t = scatter_gather(t)          # D^-1/2 A D^-1/2 with self edges
    t = indegree_norm(t)
    if not last: t = relu(t)
    if len(layers) > 3:            # residual for deep stacks
        input = linear(input, t.dim, AC_MODE_NONE)
        t = add(t, input)
    softmax_cross_entropy(t, label, mask)

``layers`` follows the reference CLI convention ``-layers 602-256-41``:
layers[0] is the input feature dim, layers[-1] the class count.
"""

from __future__ import annotations

from typing import Sequence

from .builder import Model
from ..ops.dense import AC_MODE_NONE


def build_gcn(layers: Sequence[int], dropout_rate: float = 0.5) -> Model:
    model = Model(in_dim=layers[0])
    t = model.input()
    n = len(layers)
    for i in range(1, n):
        t = model.dropout(t, dropout_rate)
        res = t
        t = model.linear(t, layers[i], AC_MODE_NONE)
        t = model.indegree_norm(t)
        t = model.scatter_gather(t)
        t = model.indegree_norm(t)
        if i != n - 1:
            t = model.relu(t)
        if n > 3:
            res = model.linear(res, t.dim, AC_MODE_NONE)
            t = model.add(t, res)
    model.softmax_cross_entropy(t)
    return model
