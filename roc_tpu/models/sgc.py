"""SGC model family: Simple Graph Convolution (Wu et al., ICML'19).

``logits = softmax(S^k X W)`` with ``S = D^-1/2 A D^-1/2`` (self edges
pre-added, the same symmetric normalization as the reference's GCN
stack, ``gnn.cc:78-91``) — all k aggregation hops applied to the RAW
features, then one linear classifier.  The reference has no such
model; SGC completes the zoo with the family whose shape makes the
full out-of-core tier exact: the aggregation prefix has no parameters,
so under ``TrainConfig(features='host')`` the trainer evaluates
``S^k X`` ONCE with every [V, F] tensor host-resident
(``core/streaming.py stream_prefix_to_host`` — the complete analog of
the reference's zero-copy residency design, ``types.cu:22-32``) and
each epoch streams only the dropout/linear head.

``layers`` follows the CLI convention: ``layers[0]`` is the input
feature dim, ``layers[-1]`` the class count; intermediate entries add
ReLU-separated linear layers after the propagation (the "SGC + MLP"
variant — classic SGC is ``layers=[F, C]``).
"""

from __future__ import annotations

from typing import Sequence

from .builder import Model
from ..ops.dense import AC_MODE_NONE


def build_sgc(layers: Sequence[int], k: int = 2,
              dropout_rate: float = 0.0) -> Model:
    if k < 1:
        raise ValueError(
            f"k must be >= 1 (k=0 is a propagation-free linear model "
            f"— surely not what an SGC user asked for), got {k}")
    model = Model(in_dim=layers[0])
    t = model.input()
    for _ in range(k):
        t = model.indegree_norm(t)
        t = model.scatter_gather(t)
        t = model.indegree_norm(t)
    n = len(layers)
    for i in range(1, n):
        t = model.dropout(t, dropout_rate)
        t = model.linear(t, layers[i], AC_MODE_NONE)
        if i != n - 1:
            t = model.relu(t)
    model.softmax_cross_entropy(t)
    return model
