"""GCNII model family: deep GCN via initial residual + identity
mapping (Chen et al., ICML'20).

Per layer l (1-indexed), with ``S = D^-1/2 A D^-1/2`` (self edges
pre-added — the reference's GCN normalization, ``gnn.cc:78-91``)::

    P_l = S H_{l-1}                       # propagation
    M_l = (1 - alpha) P_l + alpha H_0     # initial residual
    H_l = relu((1 - beta_l) M_l + beta_l M_l W_l)   # identity map

with ``beta_l = log(lam / l + 1)`` decaying over depth.  The two
mechanisms are what lets GCNII stack 16-64 layers without
oversmoothing, where the reference's plain stack degrades past ~4
(its deep-stack answer is the dense residual, ``gnn.cc:86-90``).
The reference has no such model; GCNII completes the zoo's deep end.

Both combines are the builder's fixed-scalar ``lerp`` op, so a layer
is GCN's hot aggregation path plus one extra [V, H] matmul — XLA
fuses the lerps into their producers.

``layers`` follows the CLI convention ``F-H-...-H-C``: layers[0] is
the input feature dim, layers[-1] the class count, and each
intermediate entry one GCNII layer (all must share one width H — the
initial residual adds H_0 into every layer).
"""

from __future__ import annotations

import math
from typing import Sequence

from .builder import Model
from ..ops.dense import AC_MODE_NONE


def build_gcn2(layers: Sequence[int], alpha: float = 0.1,
               lam: float = 0.5,
               dropout_rate: float = 0.5) -> Model:
    if len(layers) < 3:
        raise ValueError(
            "GCNII needs at least one hidden layer (F-H-C); for a "
            "propagation-free linear model use --model sgc")
    hidden = layers[1]
    if any(h != hidden for h in layers[1:-1]):
        raise ValueError(
            f"GCNII hidden widths must all match (the initial "
            f"residual adds H_0 into every layer), got {layers[1:-1]}")
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(f"alpha must be in [0, 1], got {alpha}")
    if lam <= 0.0:
        raise ValueError(f"lam must be > 0, got {lam}")
    model = Model(in_dim=layers[0])
    t = model.input()
    # input projection -> H_0
    t = model.dropout(t, dropout_rate)
    t = model.linear(t, hidden, AC_MODE_NONE)
    t = model.relu(t)
    h0 = t
    n_layers = len(layers) - 2
    for l in range(1, n_layers + 1):
        beta = math.log(lam / l + 1.0)
        t = model.dropout(t, dropout_rate)
        t = model.indegree_norm(t)
        t = model.scatter_gather(t)
        t = model.indegree_norm(t)
        t = model.lerp(t, h0, alpha)          # initial residual
        w = model.linear(t, hidden, AC_MODE_NONE)
        t = model.lerp(t, w, beta)            # identity mapping
        t = model.relu(t)
    t = model.dropout(t, dropout_rate)
    t = model.linear(t, layers[-1], AC_MODE_NONE)
    model.softmax_cross_entropy(t)
    return model
