"""Model builder: the reference's ``Model`` API rebuilt functionally.

The reference ``Model`` class (``gnn.h:162-203``) exposes
``dropout / linear / scatter_gather / indegree_norm / relu / sigmoid /
add / softmax_cross_entropy`` which append ``GnnOp*`` to a layer list
(e.g. ``linear.cc:20-29``); ``forward()`` walks the list and
``backward()`` walks it in reverse with hand-written gradients
(``gnn.cc:696-716``).

Here the same builder API records a static op list; :meth:`Model.apply`
interprets it inside a traced JAX function, so XLA sees one fused program
and ``jax.grad`` replaces the reference's manual autodiff driver
(including the shared-input gradient-accumulation bookkeeping of
``gnn.cc:705-713`` — JAX accumulates fanout cotangents automatically).

Graph access is abstracted behind :class:`GraphContext` so the same model
runs single-device (identity feature gather) and under ``shard_map``
(ICI ``all_gather`` feature halo — the reference's whole-region input
requirement, ``scattergather.cc:70-72``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
import numpy as np

from ..ops import dense
from ..parallel import PARTS_AXIS
from ..ops.aggregate import (aggregate, aggregate_ell, aggregate_ell_max,
                             aggregate_ell_sect, aggregate_flat_max,
                             aggregate_flat_sum)
from ..ops.dense import AC_MODE_NONE, AC_MODE_RELU, AC_MODE_SIGMOID
from ..ops.loss import masked_softmax_cross_entropy
from ..ops.norm import indegree_norm

# AggrType mirror (gnn.h:75-80); the reference declares SUM/AVG/MAX/MIN
# but implements only SUM.  Here SUM and AVG ride the symmetric-vjp CSR
# path; MAX/MIN use exact autodiff (nonlinear, so the reference's
# kernel-reuse trick does not apply; MIN = -MAX(-x)).
AGGR_SUM = "sum"
AGGR_AVG = "avg"
AGGR_MAX = "max"
AGGR_MIN = "min"


def _on_cpu() -> bool:
    """True when the default backend is CPU — Pallas TPU kernels then
    run in interpreter mode (tests / virtual-device rigs)."""
    import jax as _jax
    return _jax.default_backend() == "cpu"


@dataclass
class GraphContext:
    """Per-device view of the (partitioned) graph inside a step function.

    edge_src: int32 [E_local] source ids in *row-coordinate space* — i.e.
      indices into the feature matrix produced by ``gather_features``,
      with the dummy zero row at index ``gathered_rows``.
    edge_dst: int32 [E_local] local destination rows (sorted ascending).
    in_degree: int32 [num_rows] real in-degrees of local rows.
    num_rows: static local row count (padded).
    gathered_rows: static row count of the gathered feature matrix
      (== num_rows single-device; == parts * num_rows under shard_map).
    gather_features: the halo exchange — identity single-device,
      ``lax.all_gather`` over the mesh axis in the distributed step.
    psum: metric/loss reduction across shards (identity single-device).
    """

    edge_src: jax.Array
    edge_dst: jax.Array
    in_degree: jax.Array
    num_rows: int
    gathered_rows: int
    gather_features: Callable[[jax.Array], jax.Array] = lambda x: x
    psum: Callable[[Any], Any] = lambda x: x
    aggr_impl: str = "segment"
    chunk: int = 512
    symmetric: bool = True
    # Fused-normalization tables (aggr_fuse, see Model.fuse_norm_
    # aggregate): per-edge weights ``w = d[dst] * d[src]`` with
    # ``d = inv_sqrt_degree`` baked host-side into the aggregation
    # tables (core/ell.py ell_weight_tables / SectionedEll.
    # weight_tables, parallel/ring.py ring_weight_tables).  Shapes
    # mirror the index tables they weight.  Empty = derive ``d`` from
    # ``in_degree`` at trace time and pre/post-scale the features
    # instead (exact same numbers, two extra fused multiplies).
    ell_w: Tuple[jax.Array, ...] = ()
    sect_w: Tuple[jax.Array, ...] = ()
    ring_w: Optional[jax.Array] = None
    # bdense in-register tile scales: (d_dst [vpad], d_src [src_vpad])
    # fp32 — applied per [128, F] tile inside the einsum chunk body
    # (ops/blockdense.py), keeping the integer A-tables (and their u4
    # packing) intact
    bd_scale: Tuple[jax.Array, ...] = ()
    # ELL layout (aggr_impl == "ell"): tuple of [rows_b, width_b] index
    # arrays + [num_rows] output permutation (core/ell.py)
    ell_idx: Tuple[jax.Array, ...] = ()
    ell_row_pos: Optional[jax.Array] = None
    # forward row map per bucket ([rows_b], padding = num_rows) —
    # needed only by attention aggregation (EllTable.row_id)
    ell_row_id: Tuple[jax.Array, ...] = ()
    # Sectioned layout (aggr_impl == "sectioned"): per-section
    # [n_chunks, seg_rows, 8] sub-row tables + [n_chunks, seg_rows]
    # output rows, with static (start, size) metadata (core/ell.py
    # SectionedEll — measured 2.3x over "ell" at Reddit scale)
    sect_idx: Tuple[jax.Array, ...] = ()
    sect_sub_dst: Tuple[jax.Array, ...] = ()
    sect_meta: Tuple[Tuple[int, int], ...] = ()
    # Uniform width-8 flat layout: one [n_chunks, seg_rows, 8]
    # global-id table + [n_chunks, seg_rows] output rows, whose
    # compile size is degree-distribution-independent.  Two consumers:
    # aggr_impl == "attn_flat8" (large-graph GAT, ops/attention.py
    # gat_aggregate_flat8) and aggr_impl == "flat_sum" (the sum/max
    # path's uniform-scan consolidation, ops/aggregate.py
    # aggregate_flat_sum — ONE scan program instead of one per degree
    # bucket).  flat8_w carries the baked fused-normalization weights
    # for the flat_sum form (shape mirrors flat8_idx; None = derive d
    # from in_degree and pre/post-scale in-op).
    flat8_idx: Optional[jax.Array] = None
    flat8_dst: Optional[jax.Array] = None
    flat8_w: Optional[jax.Array] = None
    # Block-dense MXU layout (aggr_impl == "bdense"): dense [128,128]
    # adjacency tiles as uint8 multiplicity tables + tile ids, with
    # the residual (scattered) edges in the sect_* sectioned tables
    # (ops/blockdense.py; wins on community graphs whose vertex order
    # concentrates edges — see plan_blocks.occupancy)
    bd_a: Optional[jax.Array] = None
    bd_src: Optional[jax.Array] = None
    bd_dst: Optional[jax.Array] = None
    bd_vpad: int = 0
    # blocks reduced per output-tile update (>1 requires a
    # pad_plan_groups-padded plan — cuts output RMW traffic group-x)
    bd_group: int = 1
    # source tile space when it differs from bd_vpad (distributed:
    # dst tiles cover local rows, src tiles the gathered coordinates)
    bd_src_vpad: int = 0
    # halo exchange mode: "gather" = one-shot all_gather of the full
    # feature matrix (the reference's whole-region requirement);
    # "ring" = ppermute rotation overlapping per-shard aggregation
    # (parallel/ring.py) — O(V/P * F) peak memory instead of O(V * F)
    halo: str = "gather"
    # flat per-source-shard ring edge lists: (src, dst), each int32
    # [S, pair_edges] — this device's slice (parallel/ring.py)
    ring_idx: Tuple[jax.Array, ...] = ()
    # double-buffered ring schedule (ppermute issued before the local
    # scatter-accumulate, parallel/ring.py ring_aggregate): identical
    # numerics either way; False keeps the strictly sequential hop
    # order for measurement/debug (TrainConfig.ring_overlap)
    ring_overlap: bool = True
    # Chunked output head (TrainConfig.head_chunk, resolved by
    # train/trainer.resolve_head_chunk): when > 0, the LAST linear
    # (the classification head) is evaluated as a lax.scan over
    # head_chunk-row blocks (ops/dense.py linear_chunked) so the
    # head's compiled matmul shape is [head_chunk, C] — independent of
    # V_p — instead of the full [V_p, C] width.  0 = the plain
    # full-width matmul.  Values and dX are bit-identical either way;
    # dW sums the row axis blockwise (fp32 roundoff-level difference,
    # ops/dense.py linear_chunked).
    head_chunk: int = 0
    axis_name: str = PARTS_AXIS

    def _gathered_with_zero(self, x: jax.Array) -> jax.Array:
        """Halo exchange + the appended dummy zero source row that
        padding table entries point at."""
        full = self.gather_features(x)
        zero = jnp.zeros((1, full.shape[1]), dtype=full.dtype)
        return jnp.concatenate([full, zero], axis=0)

    def _sum_fwd(self, x: jax.Array) -> jax.Array:
        """Halo exchange + local CSR sum: ``out = A_p @ gather(x)``."""
        if self.halo == "ring":
            from ..parallel.ring import ring_aggregate
            return ring_aggregate(x, self.ring_idx[0], self.ring_idx[1],
                                  axis_name=self.axis_name,
                                  overlap=self.ring_overlap)
        full = self._gathered_with_zero(x)
        if self.aggr_impl == "ell":
            return aggregate_ell(full, self.ell_idx, self.ell_row_pos,
                                 self.num_rows)
        if self.aggr_impl == "sectioned":
            return aggregate_ell_sect(full, self.sect_idx,
                                      self.sect_sub_dst, self.sect_meta,
                                      self.num_rows)
        if self.aggr_impl == "flat_sum":
            return aggregate_flat_sum(full, self.flat8_idx,
                                      self.flat8_dst, self.num_rows)
        if self.aggr_impl == "bdense":
            from ..ops.blockdense import aggregate_block_dense
            out = None
            if self.bd_a is not None:
                out = aggregate_block_dense(
                    full, self.bd_a, self.bd_src, self.bd_dst,
                    self.num_rows, self.bd_vpad,
                    out_dtype=full.dtype,
                    src_vpad=self.bd_src_vpad,
                    group=self.bd_group)
            if self.sect_idx:
                res = aggregate_ell_sect(
                    full, self.sect_idx, self.sect_sub_dst,
                    self.sect_meta, self.num_rows)
                out = res if out is None else out + res
            if out is None:  # zero-edge graph
                out = jnp.zeros((self.num_rows, full.shape[1]),
                                dtype=full.dtype)
            return out
        if self.aggr_impl == "pallas":
            from ..kernels.ell_spmm import ell_aggregate_pallas
            return ell_aggregate_pallas(full, self.ell_idx,
                                        self.ell_row_pos, self.num_rows,
                                        interpret=_on_cpu())
        return aggregate(full, self.edge_src, self.edge_dst,
                         self.num_rows, impl=self.aggr_impl,
                         chunk=self.chunk)

    def aggregate_sum(self, x: jax.Array) -> jax.Array:
        """Sum aggregation with the reference's backward: for a symmetric
        global adjacency, grad_x(local) = A_p @ all_gather(cotangent) —
        the same kernel + halo exchange run on the cotangent
        (``scattergather_kernel.cu:160-170``; shard-level identity:
        row-slice_p(A^T g) = A_p g for A == A^T).  Besides parity, this
        keeps the blocked scan's backward O(chunk) memory instead of
        saving per-chunk residuals.  Set ``symmetric=False`` for exact
        autodiff through the forward (directed graphs)."""
        if not self.symmetric:
            return self._sum_fwd(x)

        @jax.custom_vjp
        def agg(x):
            return self._sum_fwd(x)

        def fwd(x):
            return agg(x), None

        def bwd(_, g):
            return (self._sum_fwd(g),)

        agg.defvjp(fwd, bwd)
        return agg(x)

    def _fused_sum_fwd(self, x: jax.Array) -> jax.Array:
        """One-pass ``D^-1/2 A D^-1/2 x`` (the GCN sandwich of
        norm -> sum-aggregate -> norm folded into the aggregation,
        Model.fuse_norm_aggregate): table-driven impls read the baked
        per-edge weights when present (zero runtime normalization);
        otherwise ``d = inv_sqrt_degree(in_degree)`` is derived at
        trace time and the features are scaled once before / the
        output once after the plain sum — the same numbers as the
        unfused chain, still inside ONE op so the multiplies fuse
        into the aggregation's reads/writes."""
        from ..ops.norm import inv_sqrt_degree
        if self.halo == "ring":
            from ..parallel.ring import ring_aggregate
            if self.ring_w is not None:
                return ring_aggregate(
                    x, self.ring_idx[0], self.ring_idx[1],
                    axis_name=self.axis_name, weights=self.ring_w,
                    overlap=self.ring_overlap)
            d = inv_sqrt_degree(self.in_degree).astype(x.dtype)
            out = ring_aggregate(x * d[:, None], self.ring_idx[0],
                                 self.ring_idx[1],
                                 axis_name=self.axis_name,
                                 overlap=self.ring_overlap)
            return out * d[:, None]
        if self.aggr_impl == "ell" and self.ell_w:
            full = self._gathered_with_zero(x)
            return aggregate_ell(full, self.ell_idx, self.ell_row_pos,
                                 self.num_rows, ell_w=self.ell_w)
        if self.aggr_impl == "sectioned" and self.sect_w:
            full = self._gathered_with_zero(x)
            return aggregate_ell_sect(full, self.sect_idx,
                                      self.sect_sub_dst, self.sect_meta,
                                      self.num_rows, sect_w=self.sect_w)
        if self.aggr_impl == "flat_sum" and self.flat8_w is not None:
            full = self._gathered_with_zero(x)
            return aggregate_flat_sum(full, self.flat8_idx,
                                      self.flat8_dst, self.num_rows,
                                      flat_w=self.flat8_w)
        if self.aggr_impl == "bdense" and self.bd_scale:
            from ..ops.blockdense import aggregate_block_dense
            full = self._gathered_with_zero(x)
            out = None
            if self.bd_a is not None:
                out = aggregate_block_dense(
                    full, self.bd_a, self.bd_src, self.bd_dst,
                    self.num_rows, self.bd_vpad,
                    out_dtype=full.dtype,
                    src_vpad=self.bd_src_vpad,
                    group=self.bd_group,
                    scale_dst=self.bd_scale[0],
                    scale_src=self.bd_scale[1])
            if self.sect_idx:
                res = aggregate_ell_sect(
                    full, self.sect_idx, self.sect_sub_dst,
                    self.sect_meta, self.num_rows, sect_w=self.sect_w)
                out = res if out is None else out + res
            if out is None:  # zero-edge graph
                out = jnp.zeros((self.num_rows, full.shape[1]),
                                dtype=full.dtype)
            return out
        if self.aggr_impl == "pallas":
            # the hand-written route (kernels/graphnorm.py): pre-scale
            # kernel on the LOCAL rows -> halo gather -> one-launch
            # ELL DMA kernel -> fused scale epilogue kernel.  The
            # activation rides outside the linear operator so the
            # symmetric vjp below stays exact.
            from ..kernels.graphnorm import (fused_ell_aggregate_pallas,
                                             indegree_norm_pallas)
            interp = _on_cpu()
            full = self._gathered_with_zero(
                indegree_norm_pallas(x, self.in_degree,
                                     interpret=interp))
            return fused_ell_aggregate_pallas(
                full, self.ell_idx, self.ell_row_pos, self.num_rows,
                inv_sqrt_degree(self.in_degree), interpret=interp)
        # gather-based impls (segment/blocked/scan): scale features
        # once per fused op, sum, scale the output
        d = inv_sqrt_degree(self.in_degree).astype(x.dtype)
        out = self._sum_fwd(x * d[:, None])
        return out * d[:, None]

    def aggregate_fused(self, x: jax.Array) -> jax.Array:
        """Fused ``S x`` with ``S = D^-1/2 A D^-1/2``.  S is symmetric
        whenever A is (diagonal scale on both sides), so the backward
        reuses the forward exactly like :meth:`aggregate_sum` —
        including the shard-level identity row-slice_p(S^T g) = S_p g.
        ``symmetric=False`` falls back to exact autodiff."""
        if not self.symmetric:
            return self._fused_sum_fwd(x)

        @jax.custom_vjp
        def agg(x):
            return self._fused_sum_fwd(x)

        def fwd(x):
            return agg(x), None

        def bwd(_, g):
            return (self._fused_sum_fwd(g),)

        agg.defvjp(fwd, bwd)
        return agg(x)

    def aggregate(self, x: jax.Array, aggr: str = AGGR_SUM) -> jax.Array:
        if aggr == AGGR_SUM:
            return self.aggregate_sum(x)
        if aggr == AGGR_AVG:
            s = self.aggregate_sum(x)
            deg = jnp.maximum(self.in_degree.astype(s.dtype), 1.0)
            return s / deg[:, None]
        if aggr == AGGR_MAX:
            return self._max_fwd(x)
        if aggr == AGGR_MIN:
            return -self._max_fwd(-x)
        raise ValueError(f"unknown aggregator: {aggr}")

    def gat_attention(self, x: jax.Array, a_src: jax.Array,
                      a_dst: jax.Array,
                      neg_slope: float = 0.2) -> jax.Array:
        """Additive-attention aggregation (ops/attention.py): per
        destination row, softmax over its neighbors of
        ``LeakyReLU(a_src.h_j + a_dst.h_i)`` weighting the neighbor
        sum.  Needs the ELL tables (every row's neighborhood in one
        bucket makes the edge softmax exact); gradients are plain
        autodiff — attention is nonlinear, the symmetric
        kernel-reuse trick does not apply."""
        if self.halo == "ring":
            raise NotImplementedError(
                "attention is not supported with halo='ring' (the ring "
                "accumulator is additive; the edge softmax needs the "
                "whole neighborhood); use halo='gather'")
        flat8 = self.aggr_impl == "attn_flat8" and \
            self.flat8_idx is not None
        if not flat8 and (self.aggr_impl not in ("ell", "pallas")
                          or not self.ell_idx):
            raise NotImplementedError(
                f"attention needs the ELL tables (aggr_impl='ell') or "
                f"the flat8 layout (aggr_impl='attn_flat8'), got "
                f"{self.aggr_impl!r}; sectioned splits a row's "
                "neighbors across sections and cannot host the edge "
                "softmax")
        from ..ops.attention import (gat_aggregate_ell,
                                     gat_aggregate_flat8,
                                     resolve_dh_chunk)
        if a_src.ndim == 1:                  # single-head vectors
            a_src = a_src[None, :]
            a_dst = a_dst[None, :]
        K, dh = a_src.shape
        full = self.gather_features(x)
        zero = jnp.zeros((1, full.shape[1]), dtype=full.dtype)
        full = jnp.concatenate([full, zero], axis=0)
        fullr = full.reshape(full.shape[0], K, dh)
        s_full = jnp.einsum("gkd,kd->gk", fullr,
                            a_src.astype(full.dtype))   # [G+1, K]
        d = jnp.einsum("vkd,kd->vk", x.reshape(x.shape[0], K, dh),
                       a_dst.astype(x.dtype))           # [num_rows, K]
        d_local = jnp.concatenate(
            [d, jnp.zeros((1, K), dtype=d.dtype)])
        if flat8:
            return gat_aggregate_flat8(full, s_full, d_local,
                                       self.flat8_idx, self.flat8_dst,
                                       self.num_rows,
                                       neg_slope=neg_slope,
                                       dh_chunk=resolve_dh_chunk(
                                           self.num_rows, K, dh))
        return gat_aggregate_ell(full, s_full, d_local, self.ell_idx,
                                 self.ell_row_id, self.ell_row_pos,
                                 self.num_rows, neg_slope=neg_slope)

    def _max_fwd(self, x: jax.Array) -> jax.Array:
        """Neighbor max; rows with no neighbors yield 0.  Dummy/padding
        sources are masked out (their zero rows must not win the max)."""
        if self.halo == "ring":
            raise NotImplementedError(
                "AGGR_MAX is not supported with halo='ring' (the ring "
                "accumulator is additive); use halo='gather'")
        full = self.gather_features(x)
        zero = jnp.zeros((1, full.shape[1]), dtype=full.dtype)
        full = jnp.concatenate([full, zero], axis=0)
        dummy = full.shape[0] - 1
        neg = jnp.asarray(-jnp.inf, dtype=full.dtype)
        if self.aggr_impl == "flat_sum":
            # the uniform-scan MAX twin (ops/aggregate.py): one scan
            # program, scatter-max combine — the large-graph MAX path
            # the resolve pass routes to past FLAT_SUM_MIN_EDGES
            out = aggregate_flat_max(full, self.flat8_idx,
                                     self.flat8_dst, self.num_rows)
        elif self.aggr_impl in ("ell", "pallas"):
            # "pallas" carries the same ELL tables; MAX is a cold path,
            # so the XLA ELL reduction serves both.  aggregate_ell_max
            # row-segments large buckets under the same 64 MiB budget
            # as the sum path.
            out = aggregate_ell_max(full, self.ell_idx,
                                    self.ell_row_pos, self.num_rows)
        else:
            if self.aggr_impl in ("blocked", "scan", "pallas_csr",
                                  "sectioned", "bdense"):
                # guard every chunked-sum impl, not just 'blocked':
                # falling through to the segment path would materialize
                # the full [E, F] per-edge matrix — an OOM on exactly
                # the large graphs those impls target
                raise NotImplementedError(
                    f"AGGR_MAX has no {self.aggr_impl!r} implementation; "
                    "use aggr_impl='ell' (big graphs; sectioned carries "
                    "no ELL tables and its additive carry can't max) or "
                    "'segment' — the segment path materializes the full "
                    "[E, F] per-edge matrix")
            g = full[self.edge_src]
            g = jnp.where((self.edge_src != dummy)[:, None], g, neg)
            out = jax.ops.segment_max(g, self.edge_dst,
                                      num_segments=self.num_rows)
        return jnp.where(jnp.isfinite(out), out, 0.0).astype(full.dtype)


def _gctx_flatten(g: GraphContext):
    children = (g.edge_src, g.edge_dst, g.in_degree, g.ell_idx,
                g.ell_row_pos, g.ring_idx, g.sect_idx, g.sect_sub_dst,
                g.ell_row_id, g.flat8_idx, g.flat8_dst, g.flat8_w,
                g.bd_a, g.bd_src, g.bd_dst, g.ell_w, g.sect_w,
                g.ring_w, g.bd_scale)
    aux = (g.num_rows, g.gathered_rows, g.gather_features, g.psum,
           g.aggr_impl, g.chunk, g.symmetric, g.halo, g.axis_name,
           g.sect_meta, g.bd_vpad, g.bd_src_vpad, g.bd_group,
           g.ring_overlap, g.head_chunk)
    return children, aux


def _gctx_unflatten(aux, children):
    (num_rows, gathered_rows, gather_features, psum, aggr_impl, chunk,
     symmetric, halo, axis_name, sect_meta, bd_vpad, bd_src_vpad,
     bd_group, ring_overlap, head_chunk) = aux
    (edge_src, edge_dst, in_degree, ell_idx, ell_row_pos, ring_idx,
     sect_idx, sect_sub_dst, ell_row_id, flat8_idx,
     flat8_dst, flat8_w, bd_a, bd_src, bd_dst, ell_w, sect_w, ring_w,
     bd_scale) = children
    return GraphContext(
        edge_src=edge_src, edge_dst=edge_dst, in_degree=in_degree,
        num_rows=num_rows, gathered_rows=gathered_rows,
        gather_features=gather_features, psum=psum,
        aggr_impl=aggr_impl, chunk=chunk, symmetric=symmetric,
        ell_idx=ell_idx, ell_row_pos=ell_row_pos, halo=halo,
        ring_idx=ring_idx, axis_name=axis_name, sect_idx=sect_idx,
        sect_sub_dst=sect_sub_dst, sect_meta=sect_meta,
        ell_row_id=ell_row_id, flat8_idx=flat8_idx,
        flat8_dst=flat8_dst, flat8_w=flat8_w, bd_a=bd_a, bd_src=bd_src,
        bd_dst=bd_dst, bd_vpad=bd_vpad, bd_src_vpad=bd_src_vpad,
        bd_group=bd_group, ring_overlap=ring_overlap,
        head_chunk=head_chunk,
        ell_w=ell_w, sect_w=sect_w, ring_w=ring_w, bd_scale=bd_scale)


# GraphContext is a pytree so the graph tables travel as jit ARGUMENTS.
# Closure-capturing them embeds the edge/ELL index arrays (hundreds of
# MB at Reddit scale) as HLO *constants* — bloating the executable and
# overflowing the axon remote-compile request (HTTP 413, observed at
# V=233k/E=115M).  The callables/static config ride in aux_data; the
# same context object is passed every step, so jit's static-equality
# check hits the cache.
jax.tree_util.register_pytree_node(GraphContext, _gctx_flatten,
                                   _gctx_unflatten)


@dataclass(frozen=True)
class TensorHandle:
    """Symbolic tensor produced by builder calls (the analog of the
    reference's ``Tensor`` value, ``gnn.h:132-158``)."""
    idx: int
    dim: int


@dataclass
class _Op:
    kind: str
    inputs: Tuple[int, ...]
    dim: int
    param: Optional[str] = None        # param-dict key for linear ops
    attrs: Dict[str, Any] = field(default_factory=dict)


class Model:
    """Builder + interpreter.  Mirrors the reference Model API
    (``gnn.h:162-203``); see module docstring."""

    def __init__(self, in_dim: int):
        self._ops: List[_Op] = [_Op("input", (), in_dim)]
        self._n_linear = 0
        self._n_gat = 0
        self._n_eps = 0
        self._loss_op: Optional[int] = None

    def uses_attention(self) -> bool:
        """True when the op list contains a gat op — such models need
        the ELL tables (trainers force aggr_impl='ell')."""
        return any(op.kind == "gat" for op in self._ops)

    def uses_max_aggregation(self) -> bool:
        """True when any scatter_gather op is MAX/MIN — those have no
        sectioned/blocked/scan implementation and no ring form, so the
        trainers' impl resolver forces 'ell' and rejects halo='ring'
        up front (same policy as attention)."""
        return any(op.kind == "scatter_gather"
                   and op.attrs.get("aggr") in (AGGR_MAX, AGGR_MIN)
                   for op in self._ops)

    def num_fused_aggregates(self) -> int:
        """Fused norm-aggregate-norm ops in the list (0 for models
        :meth:`fuse_norm_aggregate` has not been applied to, or whose
        shape has no fusable chain)."""
        return sum(op.kind == "fused_aggregate" for op in self._ops)

    def fuse_norm_aggregate(self) -> "Model":
        """Rewrite every ``indegree_norm -> scatter_gather(SUM) ->
        indegree_norm [-> relu]`` chain whose intermediates have no
        other consumer (and don't carry the loss marker) into ONE
        ``fused_aggregate`` op computing ``[relu](D^-1/2 A D^-1/2 x)``
        — the GCN normalization sandwich (``gnn.cc:78-91``) folded
        into the aggregation so the 2-3 extra full ``[V, F]`` HBM
        round trips per layer disappear (GraphContext.aggregate_fused
        picks table-baked weights or in-op scaling per impl).

        Returns a NEW Model; parameter names are untouched (the chain
        is parameter-free), so params initialized from either model
        feed both — checkpoints stay compatible.  Models with no
        matching chain come back as an equivalent copy with
        ``num_fused_aggregates() == 0``."""
        ops = self._ops
        n = len(ops)
        consumers = [0] * n
        for op in ops:
            for i in op.inputs:
                consumers[i] += 1
        loss = self._loss_op
        # chain start -> (chain end inclusive, fused activation)
        chains: Dict[int, Tuple[int, str]] = {}
        i = 1
        while i + 2 < n:
            o0, o1, o2 = ops[i], ops[i + 1], ops[i + 2]
            ok = (o0.kind == "indegree_norm"
                  and o1.kind == "scatter_gather"
                  and o1.inputs == (i,)
                  and o1.attrs.get("aggr", AGGR_SUM) == AGGR_SUM
                  and o2.kind == "indegree_norm"
                  and o2.inputs == (i + 1,)
                  and consumers[i] == 1 and consumers[i + 1] == 1
                  and loss not in (i, i + 1))
            if not ok:
                i += 1
                continue
            end, act = i + 2, AC_MODE_NONE
            if (end + 1 < n and ops[end + 1].kind == "activation"
                    and ops[end + 1].attrs.get("mode") == AC_MODE_RELU
                    and ops[end + 1].inputs == (end,)
                    and consumers[end] == 1 and loss != end):
                end += 1
                act = AC_MODE_RELU
            chains[i] = (end, act)
            i = end + 1
        fused = Model(in_dim=ops[0].dim)
        fused._n_linear = self._n_linear
        fused._n_gat = self._n_gat
        fused._n_eps = self._n_eps
        new_ops = [ops[0]]
        remap = {0: 0}
        skip_until = 0
        for i in range(1, n):
            if i in chains:
                end, act = chains[i]
                new_ops.append(_Op(
                    "fused_aggregate", (remap[ops[i].inputs[0]],),
                    ops[i].dim,
                    attrs={"aggr": AGGR_SUM, "activation": act}))
                for k in range(i, end + 1):
                    remap[k] = len(new_ops) - 1
                skip_until = end
                continue
            if i <= skip_until:
                continue
            op = ops[i]
            new_ops.append(_Op(
                op.kind, tuple(remap[k] for k in op.inputs), op.dim,
                op.param, dict(op.attrs)))
            remap[i] = len(new_ops) - 1
        fused._ops = new_ops
        fused._loss_op = remap[loss] if loss is not None else None
        return fused

    # ---- builder API (names match the reference) ----

    def input(self) -> TensorHandle:
        return TensorHandle(0, self._ops[0].dim)

    def dropout(self, t: TensorHandle, rate: float = 0.5) -> TensorHandle:
        return self._append("dropout", (t.idx,), t.dim, attrs={"rate": rate})

    def linear(self, t: TensorHandle, out_dim: int,
               activation: str = AC_MODE_NONE) -> TensorHandle:
        name = f"linear_{self._n_linear}"
        self._n_linear += 1
        return self._append("linear", (t.idx,), out_dim, param=name,
                            attrs={"activation": activation,
                                   "in_dim": t.dim})

    def indegree_norm(self, t: TensorHandle) -> TensorHandle:
        return self._append("indegree_norm", (t.idx,), t.dim)

    def scatter_gather(self, t: TensorHandle,
                       aggr: str = AGGR_SUM) -> TensorHandle:
        return self._append("scatter_gather", (t.idx,), t.dim,
                            attrs={"aggr": aggr})

    def gat_attention(self, t: TensorHandle, neg_slope: float = 0.2,
                      heads: int = 1) -> TensorHandle:
        """Attention-weighted neighbor aggregation (the GAT layer's
        core, ops/attention.py).  ``heads`` K-way splits the feature
        axis: each head attends independently over its dim/K slice and
        the outputs concatenate (the GAT paper's multi-head concat
        form).  Adds two learned [K, dim/K] attention weights
        (``gat_N_src`` / ``gat_N_dst``) to the params."""
        if t.dim % heads:
            raise ValueError(
                f"gat_attention: dim {t.dim} not divisible by "
                f"heads {heads}")
        name = f"gat_{self._n_gat}"
        self._n_gat += 1
        return self._append("gat", (t.idx,), t.dim, param=name,
                            attrs={"neg_slope": neg_slope,
                                   "heads": heads})

    def relu(self, t: TensorHandle) -> TensorHandle:
        return self._append("activation", (t.idx,), t.dim,
                            attrs={"mode": AC_MODE_RELU})

    def sigmoid(self, t: TensorHandle) -> TensorHandle:
        return self._append("activation", (t.idx,), t.dim,
                            attrs={"mode": AC_MODE_SIGMOID})

    def elu(self, t: TensorHandle) -> TensorHandle:
        """Beyond the reference's ActiMode set (gnn.h:82-86); used by
        the GAT family (models/gat.py)."""
        from ..ops.dense import AC_MODE_ELU
        return self._append("activation", (t.idx,), t.dim,
                            attrs={"mode": AC_MODE_ELU})

    def add(self, a: TensorHandle, b: TensorHandle) -> TensorHandle:
        assert a.dim == b.dim
        return self._append("add", (a.idx, b.idx), a.dim)

    def scale_add(self, a: TensorHandle, b: TensorHandle) -> TensorHandle:
        """``a + eps * b`` with a LEARNABLE scalar ``eps`` (zero-init).
        GIN's (1+eps) self-weight reduces to this on self-edged graphs:
        (1+eps)x + sum_{u != v} x_u == agg + eps*x (models/gin.py)."""
        assert a.dim == b.dim
        name = f"eps_{self._n_eps}"
        self._n_eps += 1
        return self._append("scale_add", (a.idx, b.idx), a.dim,
                            param=name)

    def mul(self, a: TensorHandle, b: TensorHandle) -> TensorHandle:
        assert a.dim == b.dim
        return self._append("mul", (a.idx, b.idx), a.dim)

    def lerp(self, a: TensorHandle, b: TensorHandle,
             alpha: float) -> TensorHandle:
        """``(1 - alpha) * a + alpha * b`` with a FIXED scalar — the
        APPNP teleport combine (models/appnp.py).  Distinct from
        :meth:`scale_add`, whose scalar is a learnable parameter."""
        assert a.dim == b.dim
        return self._append("lerp", (a.idx, b.idx), a.dim,
                            attrs={"alpha": float(alpha)})

    def softmax_cross_entropy(self, t: TensorHandle) -> TensorHandle:
        """Marks ``t`` as the logits fed to the masked CE loss (labels and
        mask arrive as apply() arguments, unlike the reference which binds
        label/mask tensors here, ``gnn.cc:92``)."""
        self._loss_op = t.idx
        return t

    def _append(self, kind: str, inputs: Tuple[int, ...], dim: int,
                param: Optional[str] = None,
                attrs: Optional[Dict[str, Any]] = None) -> TensorHandle:
        self._ops.append(_Op(kind, inputs, dim, param, attrs or {}))
        return TensorHandle(len(self._ops) - 1, dim)

    # ---- streaming support ----

    def streamable_head(self):
        """``(dropout_rate, linear_param_name, tail_model)`` when the op
        list starts ``input -> dropout -> linear`` and the first two
        intermediates have no other consumer — the pattern the
        host-feature streaming tier (core/streaming.py StreamedHead)
        can split off.  ``tail_model`` interprets ops[3:] against the
        projected ``[V, H]`` activations as its input and SHARES the
        original param names (do not call ``init_params`` on it).
        Returns None for any other head shape (e.g. GIN aggregates raw
        features; deep-GCN residuals consume the dropout output twice);
        callers fall back to in-HBM features or ring halo."""
        ops = self._ops
        if len(ops) < 4:
            return None
        if not (ops[1].kind == "dropout" and ops[1].inputs == (0,)):
            return None
        if not (ops[2].kind == "linear" and ops[2].inputs == (1,)):
            return None
        if ops[2].attrs.get("activation", AC_MODE_NONE) != AC_MODE_NONE:
            # StreamedHead computes a plain projection; a fused
            # activation would be silently dropped (and its gradient
            # mask missing from the streamed wgrad)
            return None
        for op in ops[3:]:
            if any(i < 2 for i in op.inputs):
                return None
        if self._loss_op is not None and self._loss_op < 3:
            return None
        tail = self._split_tail(2)
        return ops[1].attrs["rate"], ops[2].param, tail

    def _split_tail(self, head_out: int) -> "Model":
        """Tail model over ops past ``head_out`` (the streamed head's
        output tensor): the head output becomes the tail's input 0,
        later indices shift down, the loss marker shifts with them.
        Shared by streamable_head and streamable_agg_head — the remap
        must never drift between them."""
        ops = self._ops
        tail = Model(in_dim=ops[head_out].dim)
        for op in ops[head_out + 1:]:
            tail._ops.append(_Op(
                op.kind,
                tuple(0 if i == head_out else i - head_out
                      for i in op.inputs),
                op.dim, op.param, dict(op.attrs)))
        tail._loss_op = (self._loss_op - head_out
                         if self._loss_op is not None else None)
        return tail

    def streamable_agg_head(self):
        """``(prefix_ops, dropout_rate, linear_param, tail_model)``
        when the op list starts with a PARAMETER-FREE norm/aggregation
        chain from the input — ``(indegree_norm | scatter_gather
        SUM/AVG)+`` — followed by the ``dropout -> linear`` head
        pattern, with nothing later consuming the pre-head tensors.

        This is the SGC-family shape (aggregation applied to raw
        features, models/sgc.py): the prefix has no parameters, so the
        host tier evaluates it ONCE fully out-of-core
        (core/streaming.py stream_prefix_to_host — the reference's
        everything-host-resident ZC design, ``types.cu:22-32``) and
        every epoch then streams only the dropout/linear head.
        Returns None when there is no aggregation prefix (plain
        ``streamable_head`` covers that) or the shape doesn't match."""
        ops = self._ops
        i = 1
        while i < len(ops) and ops[i].inputs == (i - 1,) and (
                ops[i].kind in ("indegree_norm", "fused_aggregate")
                or (ops[i].kind == "scatter_gather"
                    and ops[i].attrs.get("aggr", AGGR_SUM)
                    in (AGGR_SUM, AGGR_AVG))):
            i += 1
        if i == 1 or not any(
                op.kind in ("scatter_gather", "fused_aggregate")
                for op in ops[1:i]):
            return None
        if i + 1 >= len(ops):
            return None
        if not (ops[i].kind == "dropout" and ops[i].inputs == (i - 1,)):
            return None
        if not (ops[i + 1].kind == "linear"
                and ops[i + 1].inputs == (i,)):
            return None
        if ops[i + 1].attrs.get("activation",
                                AC_MODE_NONE) != AC_MODE_NONE:
            return None
        head_out = i + 1
        for op in ops[head_out + 1:]:
            if any(j < head_out for j in op.inputs):
                return None
        # loss ON the head output is fine (classic SGC: the head linear
        # IS the classifier) — the tail degenerates to loss-on-input
        if self._loss_op is not None and self._loss_op < head_out:
            return None
        return (list(ops[1:i]), ops[i].attrs["rate"],
                ops[i + 1].param, self._split_tail(head_out))

    # ---- serving support ----

    GRAPH_OP_KINDS = ("scatter_gather", "fused_aggregate", "gat",
                      "indegree_norm")

    def precompute_split(self):
        """``(prefix_ops, head_model)`` when the op list is a
        PARAMETER-FREE propagation prefix followed by a purely dense
        (row-wise) remainder — the SGC-family shape whose serving path
        collapses to "cache ``S^k X`` once, answer with a dense MLP"
        (``roc_tpu/serve``).  ``prefix_ops`` is the op sublist the
        export step evaluates host-side ONCE (the same vocabulary
        ``stream_prefix_to_host`` runs: ``indegree_norm`` /
        ``scatter_gather`` SUM/AVG / ``fused_aggregate``);
        ``head_model`` interprets the remaining ops against gathered
        prefix rows and SHARES the original param names.  Unlike
        :meth:`streamable_agg_head` the head keeps its dropout (eval
        mode drops nothing) and may be arbitrarily deep — the only
        requirement is that no graph op (and no reach-back past the
        prefix) remains below the split.  Returns None when the model
        has no parameter-free propagation prefix or the remainder
        still touches the graph."""
        ops = self._ops
        i = 1
        while i < len(ops) and ops[i].inputs == (i - 1,) and (
                ops[i].kind in ("indegree_norm", "fused_aggregate")
                or (ops[i].kind == "scatter_gather"
                    and ops[i].attrs.get("aggr", AGGR_SUM)
                    in (AGGR_SUM, AGGR_AVG))):
            i += 1
        if i == 1 or not any(
                op.kind in ("scatter_gather", "fused_aggregate")
                for op in ops[1:i]):
            return None
        if i >= len(ops):
            return None
        for op in ops[i:]:
            if op.kind in self.GRAPH_OP_KINDS:
                return None
            if any(j < i - 1 for j in op.inputs):
                return None
        if self._loss_op is not None and self._loss_op < i - 1:
            return None
        return list(ops[1:i]), self._split_tail(i - 1)

    def to_spec(self) -> Dict[str, Any]:
        """JSON-serializable description of the built model — the
        serving manifest persists this so a cold server process
        rebuilds the EXACT op list without the builder call that made
        it (``roc_tpu/serve/export.py``)."""
        return {
            "in_dim": self._ops[0].dim,
            "ops": [{"kind": op.kind, "inputs": list(op.inputs),
                     "dim": op.dim, "param": op.param,
                     "attrs": dict(op.attrs)}
                    for op in self._ops[1:]],
            "loss_op": self._loss_op,
            "counters": [self._n_linear, self._n_gat, self._n_eps],
        }

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "Model":
        """Inverse of :meth:`to_spec`."""
        model = cls(in_dim=int(spec["in_dim"]))
        for op in spec["ops"]:
            model._ops.append(_Op(op["kind"], tuple(op["inputs"]),
                                  int(op["dim"]), op.get("param"),
                                  dict(op.get("attrs") or {})))
        model._loss_op = spec.get("loss_op")
        c = spec.get("counters") or [0, 0, 0]
        model._n_linear, model._n_gat, model._n_eps = (
            int(c[0]), int(c[1]), int(c[2]))
        return model

    # ---- params ----

    def init_params(self, key: jax.Array,
                    dtype=jnp.float32) -> Dict[str, jax.Array]:
        """Glorot-uniform for every linear weight: U(-s, s) with
        ``s = sqrt(6/(in+out))`` (``initializer_kernel.cu:38-48``)."""
        params: Dict[str, jax.Array] = {}
        for op in self._ops:
            if op.kind == "linear":
                key, sub = jax.random.split(key)
                in_dim = op.attrs["in_dim"]
                s = float(np.sqrt(6.0 / (in_dim + op.dim)))
                params[op.param] = jax.random.uniform(
                    sub, (in_dim, op.dim), dtype=dtype, minval=-s, maxval=s)
            elif op.kind == "scale_add":
                # learnable GIN eps: zero-init (the paper's GIN-0)
                params[op.param] = jnp.zeros((), dtype=dtype)
            elif op.kind == "gat":
                # per head, the attention vectors are the [2*dh] -> 1
                # projection of the GAT paper split at the concat
                # boundary — Glorot over that logical shape
                heads = op.attrs.get("heads", 1)
                dh = op.dim // heads
                s = float(np.sqrt(6.0 / (2 * dh + 1)))
                for suffix in ("src", "dst"):
                    key, sub = jax.random.split(key)
                    params[f"{op.param}_{suffix}"] = jax.random.uniform(
                        sub, (heads, dh), dtype=dtype, minval=-s,
                        maxval=s)
        return params

    # ---- interpreter ----

    def apply(self, params: Dict[str, jax.Array], feats: jax.Array,
              gctx: GraphContext, key: Optional[jax.Array] = None,
              train: bool = True) -> jax.Array:
        """Run the recorded op list; returns the logits tensor."""
        if (train and key is None and
                any(op.kind == "dropout" and op.attrs["rate"] > 0
                    for op in self._ops)):
            raise ValueError(
                "a PRNG key is required in train mode for models with "
                "dropout; pass key= or use train=False")
        vals: List[Optional[jax.Array]] = [None] * len(self._ops)
        vals[0] = feats
        n_dropout = 0
        # the output head = the LAST linear (the classifier in every
        # model family; the loss marker may sit on a later norm /
        # propagation op, e.g. GCN's final indegree_norm)
        head_idx = max((i for i, op in enumerate(self._ops)
                        if op.kind == "linear"), default=-1)
        for i, op in enumerate(self._ops[1:], start=1):
            x = vals[op.inputs[0]] if op.inputs else None
            if op.kind == "dropout":
                if train and key is not None:
                    sub = jax.random.fold_in(key, n_dropout)
                else:
                    sub = None
                n_dropout += 1
                vals[i] = dense.dropout(x, op.attrs["rate"], sub, train)
            elif op.kind == "linear":
                if gctx.head_chunk and i == head_idx \
                        and x.shape[0] > gctx.head_chunk:
                    # the classification head, chunked on the vertex
                    # axis: the compiled matmul is [head_chunk, C]
                    # regardless of V_p, so the head subprogram stays
                    # small and shape-stable (bit-identical values —
                    # each output row's dot product is unchanged; dW
                    # differs only in fp summation order)
                    vals[i] = dense.linear_chunked(
                        x, params[op.param], op.attrs["activation"],
                        gctx.head_chunk)
                else:
                    vals[i] = dense.linear(x, params[op.param],
                                           op.attrs["activation"])
            elif op.kind == "indegree_norm":
                vals[i] = indegree_norm(x, gctx.in_degree)
            elif op.kind == "scatter_gather":
                # named so the remat policy can SAVE aggregation
                # outputs: recomputing the halo gather + CSR sum in
                # backward is the one thing worth activation memory
                # (train/trainer.py remat_policy="save_aggregates")
                vals[i] = checkpoint_name(
                    gctx.aggregate(x, op.attrs["aggr"]), "aggregate")
            elif op.kind == "fused_aggregate":
                # norm -> sum -> norm [-> relu] in one op (fuse_norm_
                # aggregate).  The activation sits OUTSIDE the
                # symmetric custom_vjp (relu is nonlinear) but inside
                # this op's fusion scope, so XLA folds it into the
                # aggregation epilogue.  Same checkpoint name as
                # scatter_gather: the remat policy saves fused
                # aggregations identically.
                y = checkpoint_name(gctx.aggregate_fused(x),
                                    "aggregate")
                if op.attrs.get("activation",
                                AC_MODE_NONE) != AC_MODE_NONE:
                    y = dense.activation(y, op.attrs["activation"])
                vals[i] = y
            elif op.kind == "gat":
                vals[i] = checkpoint_name(
                    gctx.gat_attention(
                        x, params[f"{op.param}_src"],
                        params[f"{op.param}_dst"],
                        neg_slope=op.attrs["neg_slope"]), "aggregate")
            elif op.kind == "activation":
                vals[i] = dense.activation(x, op.attrs["mode"])
            elif op.kind == "add":
                vals[i] = vals[op.inputs[0]] + vals[op.inputs[1]]
            elif op.kind == "scale_add":
                eps = params[op.param].astype(vals[op.inputs[0]].dtype)
                vals[i] = (vals[op.inputs[0]]
                           + eps * vals[op.inputs[1]])
            elif op.kind == "mul":
                vals[i] = vals[op.inputs[0]] * vals[op.inputs[1]]
            elif op.kind == "lerp":
                al = op.attrs["alpha"]
                vals[i] = ((1.0 - al) * vals[op.inputs[0]]
                           + al * vals[op.inputs[1]])
            else:
                raise ValueError(f"unknown op kind {op.kind}")
        out_idx = self._loss_op if self._loss_op is not None else -1
        return vals[out_idx]

    def loss_fn(self, params: Dict[str, jax.Array], feats: jax.Array,
                labels: jax.Array, mask: jax.Array, gctx: GraphContext,
                key: Optional[jax.Array] = None,
                train: bool = True) -> Tuple[jax.Array, jax.Array]:
        """(summed masked CE, logits) — the differentiable objective whose
        gradient equals the reference's ``softmax - onehot`` on train rows
        (``softmax_kernel.cu:19-33``)."""
        logits = self.apply(params, feats, gctx, key=key, train=train)
        loss = masked_softmax_cross_entropy(logits, labels, mask)
        return gctx.psum(loss), logits
