"""GAT model family (graph attention networks).

The reference has no attention model — its only aggregation is the
unweighted CSR sum (``scattergather_kernel.cu:20-76``).  GAT is the
framework's TPU-native extension, showing the op set generalizes past
the reference's fixed GCN stack: the single-head additive attention of
Velickovic et al. (ICLR'18), expressed with the builder ops::

    t = dropout(t, rate)
    t = linear(t, layers[i], AC_MODE_NONE)     # h = W x
    t = gat_attention(t)                       # softmax-weighted sum
    if not last: t = elu(t)

The edge softmax runs exactly on the ELL layout (every row's whole
neighborhood in one bucket — ops/attention.py has the mechanism);
trainers force ``aggr_impl='ell'`` for attention models.
"""

from __future__ import annotations

from typing import Sequence

from .builder import Model
from ..ops.dense import AC_MODE_NONE


def build_gat(layers: Sequence[int], dropout_rate: float = 0.5,
              neg_slope: float = 0.2, heads: int = 1) -> Model:
    """``heads`` applies to the hidden layers (multi-head concat —
    each hidden dim must divide by it); the output layer is always
    single-head, as in the paper."""
    model = Model(in_dim=layers[0])
    t = model.input()
    n = len(layers)
    for i in range(1, n):
        last = i == n - 1
        t = model.dropout(t, dropout_rate)
        t = model.linear(t, layers[i], AC_MODE_NONE)
        t = model.gat_attention(t, neg_slope=neg_slope,
                                heads=1 if last else heads)
        if not last:
            t = model.elu(t)
    model.softmax_cross_entropy(t)
    return model
