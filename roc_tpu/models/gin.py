"""GIN (Graph Isomorphism Network) with sum aggregation + MLP.

Fills BASELINE.md config 5 (GIN sum-aggregation + MLP, 8-way partition).
Standard GIN layer with eps = 0::

    h = MLP( x + sum_{u in N(v)} x_u )
    MLP = linear -> ReLU -> linear

The sum aggregation is the reference's ScatterGather op verbatim
(``scattergather_kernel.cu:20-76``), so GIN rides the same symmetric-vjp
CSR path; the self-edge already present in the graph (``gnn.cc:756``)
makes the explicit ``x +`` a second self-contribution, matching the
(1 + eps)·x formulation at eps = 1 over self-edge-free neighborhoods —
we keep the explicit add so GIN works on the same self-edged graphs the
rest of the framework assumes.
"""

from __future__ import annotations

from typing import Sequence

from .builder import AGGR_SUM, Model
from ..ops.dense import AC_MODE_NONE, AC_MODE_RELU


def build_gin(layers: Sequence[int], dropout_rate: float = 0.5,
              mlp_hidden: int = 0, learn_eps: bool = False) -> Model:
    """``mlp_hidden`` == 0 sizes each MLP's hidden dim as
    ``max(in, out)`` of its layer — NEVER the bare class count: a
    ReLU hidden of width ``num_classes`` (3 on the test fixtures) is a
    biasless bottleneck that can die for a whole class region and
    never recover (observed: exact-zero logits for every node of one
    class, train acc pinned across lr/epochs).  ``learn_eps`` swaps
    the fixed self-contribution for the paper's learnable epsilon: on
    self-edged graphs (1+eps)x + sum_{u != v} x_u == agg + eps*x, so
    the layer becomes ``scale_add(agg, x)`` with a zero-init scalar
    (GIN-0 start)."""
    model = Model(in_dim=layers[0])
    t = model.input()
    n = len(layers)
    for i in range(1, n):
        t = model.dropout(t, dropout_rate)
        agg = model.scatter_gather(t, aggr=AGGR_SUM)
        if learn_eps:
            t = model.scale_add(agg, t)
        else:
            t = model.add(t, agg)
        hidden = mlp_hidden or max(layers[i], layers[i - 1])
        t = model.linear(t, hidden, AC_MODE_RELU)
        t = model.linear(t, layers[i], AC_MODE_NONE)
        if i != n - 1:
            t = model.relu(t)
    model.softmax_cross_entropy(t)
    return model
