"""Model zoo.  :func:`model_builders` is THE name → builder registry —
the training CLI (``train/cli.py``), the serve export CLI
(``serve/export.py``), and the benchmarks all resolve ``--model``
through it, so the vocabularies can never diverge."""

from __future__ import annotations

from typing import Callable, Dict


def model_builders() -> Dict[str, Callable]:
    """Lazily imported so ``import roc_tpu.models`` stays jax-light."""
    from .appnp import build_appnp
    from .gat import build_gat
    from .gcn import build_gcn
    from .gcn2 import build_gcn2
    from .gin import build_gin
    from .sage import build_sage
    from .sgc import build_sgc
    return {"gcn": build_gcn, "sage": build_sage, "gin": build_gin,
            "gat": build_gat, "sgc": build_sgc, "appnp": build_appnp,
            "gcn2": build_gcn2}
