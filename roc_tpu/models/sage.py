"""GraphSAGE (mean aggregation) model family.

The reference declares AGGR_AVG in its AggrType enum (``gnn.h:75-80``)
but never builds a SAGE model; this fills BASELINE.md config 3
(GraphSAGE mean-aggregation + GraphNorm + dropout).  Standard SAGE-mean
layer, expressed with the builder ops::

    h = W_self · x  +  W_neigh · mean_{u in N(v)} x_u
    (concat-then-linear == sum of two linears, so no concat op needed)

and ReLU between layers.  ``use_norm=True`` swaps the mean aggregator
for the reference's symmetric GraphNorm form — InDegreeNorm on both
sides of a SUM aggregation, i.e. D^-1/2 A D^-1/2 (the norm pair around
AVG would triple-normalize: D^-1/2 D^-1 A D^-1/2).
"""

from __future__ import annotations

from typing import Sequence

from .builder import AGGR_AVG, AGGR_MAX, AGGR_SUM, Model
from ..ops.dense import AC_MODE_NONE, AC_MODE_RELU


def build_sage(layers: Sequence[int], dropout_rate: float = 0.5,
               use_norm: bool = False,
               aggregator: str = "mean") -> Model:
    """``aggregator``: "mean" (the default SAGE-mean layer) or "pool"
    (max-pooling: neighbors pass through a learned ReLU projection and
    the elementwise MAX over the neighborhood is taken — Hamilton et
    al.'s pool aggregator, using the framework's AGGR_MAX path).
    ``use_norm`` swaps mean for the symmetric GraphNorm form (mean
    only)."""
    if aggregator not in ("mean", "pool"):
        raise ValueError(f"unknown SAGE aggregator {aggregator!r}; "
                         "expected 'mean' or 'pool'")
    if aggregator == "pool" and use_norm:
        raise ValueError("use_norm applies to the mean aggregator "
                         "(GraphNorm replaces the mean, not the pool)")
    model = Model(in_dim=layers[0])
    t = model.input()
    n = len(layers)
    for i in range(1, n):
        t = model.dropout(t, dropout_rate)
        self_proj = model.linear(t, layers[i], AC_MODE_NONE)
        neigh = t
        if aggregator == "pool":
            # learned pre-pool transform, then neighborhood max
            neigh = model.linear(neigh, layers[i], AC_MODE_RELU)
            neigh = model.scatter_gather(neigh, aggr=AGGR_MAX)
        elif use_norm:
            neigh = model.indegree_norm(neigh)
            neigh = model.scatter_gather(neigh, aggr=AGGR_SUM)
            neigh = model.indegree_norm(neigh)
        else:
            neigh = model.scatter_gather(neigh, aggr=AGGR_AVG)
        neigh_proj = model.linear(neigh, layers[i], AC_MODE_NONE)
        t = model.add(self_proj, neigh_proj)
        if i != n - 1:
            t = model.relu(t)
    model.softmax_cross_entropy(t)
    return model
