"""Typed serving failures: the ONLY ways a request is allowed to fail.

The robustness contract (tests/test_serve_robustness.py drills it
through the real export→load→load-generator path) is that an accepted
request either completes with a correct answer or fails with one of
these types — never a hang, never a bare RuntimeError, never a wrong
value.  Clients branch on the type; the router maps replica-side
failures onto the same vocabulary so one `except ServeError` covers a
single-process `Server` and a replicated `Router` alike.

Import-light on purpose (no jax, no numpy): the router's client side
and the sentinel-adjacent accounting import these without a backend.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for every typed serving failure."""


class ServeTimeout(ServeError):
    """The request's ``deadline_ms`` expired before a dispatch could
    complete it.  Delivered at a microbatch boundary, so a deadline'd
    request resolves within ~one microbatch of its deadline — the
    "never a hang" half of the contract."""


class ServeOverload(ServeError):
    """Load shed: the bounded admission queue (or the router's
    in-flight cap) was full at submit time.  Raised immediately — an
    overloaded server fails fast instead of queueing unboundedly and
    timing everyone out."""


class ServeClosed(ServeError):
    """The server/router is closed (or draining): late ``submit()``
    calls are rejected with this instead of racing the dispatcher
    shutdown."""


class GatherError(ServeError):
    """The cross-shard gather leg failed: a sliced replica could not
    fetch rows it does not own at the microbatch's captured table
    version (owner refused the version pin twice, owner died
    mid-fetch, no gather path configured, or the microbatch's foreign
    set exceeded the staging halo).  Retryable at the router level —
    a re-dispatch captures a fresh version and gathers again."""


class ReplicaLost(ServeError):
    """Router-internal: the replica holding this request died.  Client
    code normally never sees it — the router requeues the request onto
    a surviving replica; it surfaces only when NO replica can serve
    the request's shard anymore."""
