"""Low-latency inference tier (the ROADMAP's "serve the graph" item).

- :mod:`.export` — ``python -m roc_tpu.export``: checkpoint/config →
  serving artifact (AOT-warmed predict executables + manifest).
- :mod:`.predictor` — the bucketed query engine (full-graph and
  precomputed-propagation backends).
- :mod:`.propagation` — ``S^k X`` tables + incremental edge-append
  invalidation.
- :mod:`.server` — the coalescing microbatch request queue.
"""

from .predictor import SERVE_BUCKETS, Predictor, bucket_for  # noqa: F401
from .server import Server  # noqa: F401
