"""Low-latency inference tier (the ROADMAP's "serve the graph" item).

- :mod:`.export` — ``python -m roc_tpu.export``: checkpoint/config →
  serving artifact (AOT-warmed predict executables + manifest).
- :mod:`.predictor` — the bucketed query engine (full-graph and
  precomputed-propagation backends), with atomically-published
  versioned tables.
- :mod:`.propagation` — ``S^k X`` tables + incremental edge-append
  invalidation.
- :mod:`.server` — the coalescing microbatch request queue (deadlines,
  backpressure, graceful drain).
- :mod:`.router` / :mod:`.replica` — N replica subprocesses behind one
  ``submit`` (least-loaded dispatch, failover, hedging).
- :mod:`.errors` — the typed failure vocabulary every layer shares.
"""

from .errors import (ReplicaLost, ServeClosed, ServeError,  # noqa: F401
                     ServeOverload, ServeTimeout)
from .predictor import (SERVE_BUCKETS, Predictor, TableVersion,  # noqa: F401
                        bucket_for)
from .server import Server, ServeResult  # noqa: F401
