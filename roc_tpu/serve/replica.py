"""Serve replica worker: ONE server process behind the Router.

``python -m roc_tpu.serve.replica <artifact_dir> --replica N`` is what
the :class:`~roc_tpu.serve.router.Router` spawns, N times, over the
SAME exported artifact: each replica cold-loads the predictor
(``load_predictor`` — zero new compiles against a warm persistent
cache), runs a :class:`~roc_tpu.serve.server.Server`, and speaks a
line-JSON protocol over stdin/stdout:

stdin  (router → replica)
    ``{"kind": "req", "id": i, "ids": [...], "deadline_ms": f|null,
    "rid": s|null}``
    one request — ``rid`` is the router-minted request id the
    distributed trace connects on (PR 17): the Server stamps it into
    the microbatch span this request rides, so ``python -m
    roc_tpu.timeline --request RID`` follows one request across the
    router and replica lanes
    ``{"kind": "close"}``  drain-and-exit (stdin EOF means the same)

stdout (replica → router)
    ``{"kind": "ready", "replica": n, "num_nodes": V, ...}``  once
    ``{"kind": "hb", "inflight": q, "served": n}``  liveness beats
    ``{"kind": "res", "id": i, "ok": true, "rows": [[...]],
    "version": v}``  or ``{"kind": "res", "id": i, "ok": false,
    "error": "<TypeName>", "msg": ..., "retryable": bool}``
    ``{"kind": "drained", "clean": bool}``  final line before exit 0

Lifecycle is the PR-8 preemption contract applied to serving: a
:class:`~roc_tpu.resilience.preempt.PreemptionGuard` turns SIGTERM
into a **graceful drain** — stop admitting (late requests fail typed
``ServeClosed``), finish every in-flight microbatch, write the
``drained`` line, exit 0.  The scheduler's grace window ends a serving
process the same way it ends a training epoch.

Fault drills arm per replica through the standard
``ROC_TPU_FAULT=site:epoch:proc`` grammar: ``proc`` is THIS replica's
index (pinned via ``inject.note_proc_index``), ``epoch`` the
microbatch index (``inject.serve_batch_hooks`` in the Server
dispatcher).  ``serve_io`` comes back to the router as a *retryable*
error; ``replica_sigkill``/``replica_stall`` are what the failover and
hedging paths exist for.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from . import errors as serve_errors

# failure types the ROUTER may transparently re-dispatch to another
# replica: transient I/O (the serve_io drill class) and anything that
# names this replica's internal state rather than the request.
# Deadline/shed/closed failures are the CONTRACT — they propagate
# typed to the client, never retried into a second replica's queue.
RETRYABLE = (OSError,)

HB_ENV = "ROC_TPU_SERVE_HB_S"
DEFAULT_HB_S = 1.0


def hb_interval() -> float:
    try:
        # env-string parse, not a device fetch: roc-lint: ok=host-sync-hot-path
        return max(0.05, float(os.environ.get(HB_ENV, DEFAULT_HB_S)))
    except ValueError:
        return DEFAULT_HB_S


class _Wire:
    """stdout writer: one lock, one flushed line per message — the
    same serializer-lock shape as the event bus's JSONL sink."""

    def __init__(self, stream):
        self._stream = stream
        self._lock = threading.Lock()

    def send(self, obj: Dict[str, Any]) -> None:
        line = json.dumps(obj)
        with self._lock:
            # the lock IS the line serializer (dispatcher callbacks,
            # the hb thread, and the main thread all write); the hold
            # is one buffered line + flush: roc-lint: ok=blocking-under-lock
            self._stream.write(line + "\n")
            # same bounded hold: roc-lint: ok=blocking-under-lock
            self._stream.flush()


def _error_payload(req_id: int, e: BaseException) -> Dict[str, Any]:
    # the Server wraps dispatch failures in ServeError with the raw
    # exception chained — retryability reads through the chain, so an
    # injected serve_io OSError still comes back retryable
    retryable = isinstance(e, RETRYABLE) \
        or isinstance(getattr(e, "__cause__", None), RETRYABLE)
    return {"kind": "res", "id": req_id, "ok": False,
            "error": type(e).__name__, "msg": str(e)[:300],
            "retryable": retryable}


def serve_loop(server, wire: _Wire, replica: int,
               drain_timeout_s: float = 30.0) -> bool:
    """Read requests until stdin EOF, a ``close`` message, or a
    preemption signal; then drain.  Returns the drain verdict."""
    from ..obs.events import emit
    from ..resilience import preempt

    inflight = [0]
    served = [0]
    stop = threading.Event()

    def on_done(req_id):
        def cb(fut):
            inflight[0] -= 1   # dispatcher-thread only; hb reads racily
            try:
                rows = fut.result()
                served[0] += 1
                wire.send({"kind": "res", "id": req_id, "ok": True,
                           "rows": rows.tolist(),
                           "version": int(getattr(rows, "version",
                                                  0)),
                           "qmode": getattr(rows, "qmode", "off")})
            except BaseException as e:  # noqa: BLE001 - wire it back
                wire.send(_error_payload(req_id, e))
        return cb

    def hb_loop():
        iv = hb_interval()
        while not stop.wait(iv):
            wire.send({"kind": "hb", "inflight": inflight[0],
                       "served": served[0],
                       "mono": round(time.monotonic(), 3)})

    def read_loop():
        for line in sys.stdin:
            if stop.is_set():
                break
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            kind = msg.get("kind")
            if kind == "close":
                break
            req_id = msg.get("id")
            if kind != "req":
                # explicit unknown-kind rejection: a typo'd or
                # future kind must fail LOUD, not be silently
                # treated as a request (the wire-vocabulary bug
                # class roc-lint level eight audits for)
                emit("serve",
                     f"replica {replica}: rejecting unknown wire "
                     f"kind {kind!r}", console=False,
                     kind_rejected=str(kind), replica=replica)
                if req_id is not None:
                    wire.send({"kind": "res", "id": req_id,
                               "ok": False, "error": "ServeError",
                               "msg": f"unknown wire kind {kind!r}",
                               "retryable": False})
                continue
            if req_id is None:
                continue
            inflight[0] += 1
            fut = server.submit(msg.get("ids") or [],
                                deadline_ms=msg.get("deadline_ms"),
                                rid=msg.get("rid"))
            fut.add_done_callback(on_done(req_id))
        stop.set()

    hb = threading.Thread(target=hb_loop, name="replica:hb",
                          daemon=True)
    reader = threading.Thread(target=read_loop, name="replica:stdin",
                              daemon=True)
    hb.start()
    reader.start()
    # the main thread owns the lifecycle: SIGTERM (preemption guard
    # flag) or reader exit (EOF / close message) both funnel into ONE
    # drain path — readline retries EINTR (PEP 475), so the signal
    # can only be acted on from a poll loop like this
    while not stop.wait(0.05):
        if preempt.requested():
            stop.set()
    clean = server.drain(timeout=drain_timeout_s)
    hb.join(timeout=2.0)
    wire.send({"kind": "drained", "clean": bool(clean),
               "replica": replica, "served": served[0]})
    return clean


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m roc_tpu.serve.replica", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("artifact", help="exported serving artifact dir")
    ap.add_argument("--replica", type=int, default=0,
                    help="router-assigned replica index (the :proc "
                         "arm of serve fault drills)")
    ap.add_argument("--shard", default=None,
                    help="lo:hi node range this replica ADVERTISES "
                         "(routing metadata for the future 2-D mesh; "
                         "the artifact still carries the full table)")
    ap.add_argument("--max-wait-ms", type=float, default=0.2)
    ap.add_argument("--max-queue", type=int, default=None)
    ap.add_argument("--drain-timeout", type=float, default=30.0)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    from ..obs.events import set_clock_identity
    from ..resilience import inject, preempt
    # identity FIRST: the fault arm and every event this process emits
    # (its timeline lane included) carry the replica index
    inject.note_proc_index(args.replica)
    set_clock_identity(proc=args.replica)
    preempt.install()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..obs.heartbeat import Heartbeat
    from ..utils.compile_cache import enable_compile_cache
    from .export import load_predictor
    from .server import DEFAULT_MAX_QUEUE, Server
    enable_compile_cache()
    with Heartbeat(f"replica{args.replica} loading artifact"):
        pred = load_predictor(args.artifact)
    shard = None
    if args.shard:
        lo, hi = args.shard.split(":")
        shard = [int(lo), int(hi)]
    wire = _Wire(sys.stdout)
    server = Server(
        pred, max_wait_ms=args.max_wait_ms,
        name=f"replica{args.replica}",
        max_queue=(DEFAULT_MAX_QUEUE if args.max_queue is None
                   else args.max_queue))
    wire.send({"kind": "ready", "replica": args.replica,
               "pid": os.getpid(),
               "num_nodes": int(pred.num_nodes),
               "num_classes": pred.num_classes,
               "buckets": list(pred.buckets),
               "backend": pred.backend, "shard": shard,
               "quant": pred.quant})
    serve_loop(server, wire, args.replica,
               drain_timeout_s=args.drain_timeout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
