"""Serve replica worker: ONE server process behind the Router.

``python -m roc_tpu.serve.replica <artifact_dir> --replica N`` is what
the :class:`~roc_tpu.serve.router.Router` spawns, N times, over the
SAME exported artifact: each replica cold-loads the predictor
(``load_predictor`` — zero new compiles against a warm persistent
cache), runs a :class:`~roc_tpu.serve.server.Server`, and speaks a
line-JSON protocol over stdin/stdout:

stdin  (router → replica)
    ``{"kind": "req", "id": i, "ids": [...], "deadline_ms": f|null,
    "rid": s|null}``
    one request — ``rid`` is the router-minted request id the
    distributed trace connects on (PR 17): the Server stamps it into
    the microbatch span this request rides, so ``python -m
    roc_tpu.timeline --request RID`` follows one request across the
    router and replica lanes
    ``{"kind": "close"}``  drain-and-exit (stdin EOF means the same)

stdout (replica → router)
    ``{"kind": "ready", "replica": n, "num_nodes": V, ...}``  once
    ``{"kind": "hb", "inflight": q, "served": n}``  liveness beats
    ``{"kind": "res", "id": i, "ok": true, "rows": [[...]],
    "version": v}``  or ``{"kind": "res", "id": i, "ok": false,
    "error": "<TypeName>", "msg": ..., "retryable": bool}``
    ``{"kind": "drained", "clean": bool}``  final line before exit 0

Lifecycle is the PR-8 preemption contract applied to serving: a
:class:`~roc_tpu.resilience.preempt.PreemptionGuard` turns SIGTERM
into a **graceful drain** — stop admitting (late requests fail typed
``ServeClosed``), finish every in-flight microbatch, write the
``drained`` line, exit 0.  The scheduler's grace window ends a serving
process the same way it ends a training epoch.

Fault drills arm per replica through the standard
``ROC_TPU_FAULT=site:epoch:proc`` grammar: ``proc`` is THIS replica's
index (pinned via ``inject.note_proc_index``), ``epoch`` the
microbatch index (``inject.serve_batch_hooks`` in the Server
dispatcher).  ``serve_io`` comes back to the router as a *retryable*
error; ``replica_sigkill``/``replica_stall`` are what the failover and
hedging paths exist for.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from . import errors as serve_errors

# failure types the ROUTER may transparently re-dispatch to another
# replica: transient I/O (the serve_io drill class) and anything that
# names this replica's internal state rather than the request.
# Deadline/shed/closed failures are the CONTRACT — they propagate
# typed to the client, never retried into a second replica's queue.
# GatherError (PR 20) is replica-internal too: a failed cross-shard
# row fetch says nothing about the request — a re-dispatch captures a
# fresh table version and gathers again.
RETRYABLE = (OSError, serve_errors.GatherError)

GATHER_TIMEOUT_ENV = "ROC_TPU_GATHER_TIMEOUT_S"
DEFAULT_GATHER_TIMEOUT_S = 10.0

HB_ENV = "ROC_TPU_SERVE_HB_S"
DEFAULT_HB_S = 1.0


def hb_interval() -> float:
    try:
        # env-string parse, not a device fetch: roc-lint: ok=host-sync-hot-path
        return max(0.05, float(os.environ.get(HB_ENV, DEFAULT_HB_S)))
    except ValueError:
        return DEFAULT_HB_S


class _Wire:
    """stdout writer: one lock, one flushed line per message — the
    same serializer-lock shape as the event bus's JSONL sink."""

    def __init__(self, stream):
        self._stream = stream
        self._lock = threading.Lock()

    def send(self, obj: Dict[str, Any]) -> None:
        line = json.dumps(obj)
        with self._lock:
            # the lock IS the line serializer (dispatcher callbacks,
            # the hb thread, and the main thread all write); the hold
            # is one buffered line + flush: roc-lint: ok=blocking-under-lock
            self._stream.write(line + "\n")
            # same bounded hold: roc-lint: ok=blocking-under-lock
            self._stream.flush()


def _rows_payload(gid: Any, ids: List[int], rows: Any, version: int,
                  qmode: str, scales: Any, replica: int,
                  error: Optional[str]) -> Dict[str, Any]:
    # ONE wire shape for both halves of a row-fetch answer: ok answers
    # carry the stored rows (+ per-row scales when quantized, shipped
    # as storage-byte lists), refusals carry "error" with rows empty —
    # the requester's version pin decides what to do with a refusal
    return {"kind": "rows", "gid": gid, "ids": ids, "rows": rows,
            "version": version, "qmode": qmode, "scales": scales,
            "replica": replica, "error": error}


class _GatherClient:
    """The REQUESTER half of the cross-shard gather leg (PR 20):
    ``gather(ids, version)`` splits a microbatch's unique foreign ids
    by the artifact's shard plan, sends one version-pinned
    ``fetch_rows`` per owning shard (the router forwards each to the
    owner and relays the ``rows`` answer back by gid), blocks until
    every answer lands, and merges them into the
    ``(values, scales, version, qmode)`` tuple
    ``Predictor._stage_foreign`` stages.  Any refusal (version
    mismatch at the owner, owner death, un-owned ids) reports version
    -1 so the predictor's one-retry-then-``GatherError`` pin logic
    drives the outcome — the gather never silently mixes versions."""

    def __init__(self, wire: "_Wire", plan: List[List[int]],
                 qmode: str, replica: int,
                 timeout_s: Optional[float] = None):
        self._wire = wire
        self._plan = [(int(lo), int(hi)) for lo, hi in plan]
        self._qmode = qmode
        self._replica = replica
        if timeout_s is None:
            try:
                # env-string parse, not a device fetch
                timeout_s = float(os.environ.get(  # roc-lint: ok=host-sync-hot-path
                    GATHER_TIMEOUT_ENV, DEFAULT_GATHER_TIMEOUT_S))
            except ValueError:
                timeout_s = DEFAULT_GATHER_TIMEOUT_S
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._seq = 0
        self._pending: Dict[str, Dict[str, Any]] = {}

    def on_rows(self, msg: Dict[str, Any]) -> None:
        """stdin-reader delivery of one ``rows`` answer."""
        with self._lock:
            call = self._pending.pop(str(msg.get("gid")), None)
        if call is None:
            return      # late answer for a timed-out gather
        call["got"][str(msg.get("gid"))] = msg
        if set(call["got"]) >= call["need"]:
            call["ev"].set()

    def gather(self, ids, version: int):
        import numpy as np
        ids = np.asarray(ids, dtype=np.int64).ravel()
        call: Dict[str, Any] = {"need": set(), "got": {},
                                "ev": threading.Event()}
        sends: List[Any] = []
        with self._lock:
            for lo, hi in self._plan:
                m = (ids >= lo) & (ids < hi)
                if not m.any():
                    continue
                gid = f"r{self._replica}g{self._seq}"
                self._seq += 1
                self._pending[gid] = call
                call["need"].add(gid)
                sends.append((gid, ids[m]))
        for gid, sub in sends:
            self._wire.send({"kind": "fetch_rows", "gid": gid,
                             "ids": [int(i) for i in sub],
                             "version": int(version)})
        if not call["ev"].wait(self._timeout_s):
            with self._lock:
                for gid in call["need"]:
                    self._pending.pop(gid, None)
            raise serve_errors.GatherError(
                f"cross-shard gather of {ids.size} row(s) timed out "
                f"after {self._timeout_s}s (pinned to v{version})")
        return self._merge(ids, list(call["got"].values()), version)

    def _merge(self, ids, msgs: List[Dict[str, Any]], version: int):
        import numpy as np

        from ..obs.events import emit
        from .quant import from_storage_bytes
        for m in msgs:
            if m.get("error") or int(m.get("version", -1)) != \
                    int(version):
                emit("serve", f"replica {self._replica}: gather "
                     f"refused by owner: {m.get('error')!r} "
                     f"(owner v{m.get('version')}, pinned "
                     f"v{version})", console=False,
                     kind="gather_refused", replica=self._replica)
                return None, None, -1, str(m.get("qmode", "off"))
        qmode = str(msgs[0].get("qmode", "off"))
        byid: Dict[int, Any] = {}
        sbyid: Dict[int, float] = {}
        for m in msgs:
            if qmode == "off":
                for i, r in zip(m["ids"], m["rows"]):
                    byid[int(i)] = np.asarray(r, dtype=np.float32)
            else:
                codes = from_storage_bytes(
                    np.asarray(m["rows"], dtype=np.uint8), qmode)
                for j, i in enumerate(m["ids"]):
                    byid[int(i)] = codes[j]
                    # wire-JSON scalar, not a device fetch
                    sbyid[int(i)] = float(m["scales"][j])  # roc-lint: ok=host-sync-hot-path
        vals = np.stack([byid[int(i)] for i in ids])
        scales = (None if qmode == "off" else
                  np.asarray([sbyid[int(i)] for i in ids],
                             dtype=np.float32))
        return vals, scales, int(version), qmode


def _answer_fetch(server, wire: "_Wire", replica: int,
                  msg: Dict[str, Any]) -> None:
    """The OWNER half: serve a version-pinned row fetch from the
    predictor's host mirror (reader-thread work — a host copy, never a
    device round trip).  Refusals (version mismatch, un-owned ids, no
    predictor) answer with the error variant of ``rows``."""
    gid = msg.get("gid")
    ids = [int(i) for i in (msg.get("ids") or [])]
    version = int(msg.get("version") or 0)
    pred = getattr(server, "pred", None)
    try:
        if pred is None or not hasattr(pred, "read_rows"):
            raise serve_errors.GatherError(
                "this replica has no row-fetch surface")
        vals, scales, ver, qmode = pred.read_rows(ids, version)
        if qmode != "off":
            from .quant import to_storage_bytes
            rows_w = to_storage_bytes(vals).tolist()
            scales_w = [float(s) for s in scales]
        else:
            rows_w = [[float(x) for x in r] for r in vals]
            scales_w = None
        wire.send(_rows_payload(gid, ids, rows_w, int(ver), qmode,
                                scales_w, replica, None))
    except BaseException as e:  # noqa: BLE001 - wire the refusal back
        wire.send(_rows_payload(gid, ids, [], version, "off", None,
                                replica, f"{type(e).__name__}: "
                                f"{str(e)[:300]}"))


def _error_payload(req_id: int, e: BaseException) -> Dict[str, Any]:
    # the Server wraps dispatch failures in ServeError with the raw
    # exception chained — retryability reads through the chain, so an
    # injected serve_io OSError still comes back retryable
    retryable = isinstance(e, RETRYABLE) \
        or isinstance(getattr(e, "__cause__", None), RETRYABLE)
    return {"kind": "res", "id": req_id, "ok": False,
            "error": type(e).__name__, "msg": str(e)[:300],
            "retryable": retryable}


def serve_loop(server, wire: _Wire, replica: int,
               drain_timeout_s: float = 30.0) -> bool:
    """Read requests until stdin EOF, a ``close`` message, or a
    preemption signal; then drain.  Returns the drain verdict."""
    from ..obs.events import emit
    from ..resilience import preempt

    inflight = [0]
    served = [0]
    stop = threading.Event()

    def on_done(req_id):
        def cb(fut):
            inflight[0] -= 1   # dispatcher-thread only; hb reads racily
            try:
                rows = fut.result()
                served[0] += 1
                shard = getattr(rows, "shard", None)
                gms = getattr(rows, "gather_ms", None)
                wire.send({"kind": "res", "id": req_id, "ok": True,
                           "rows": rows.tolist(),
                           "version": int(getattr(rows, "version",
                                                  0)),
                           "qmode": getattr(rows, "qmode", "off"),
                           "shard": (None if shard is None
                                     else list(shard)),
                           "gather_ms": gms})
            except BaseException as e:  # noqa: BLE001 - wire it back
                wire.send(_error_payload(req_id, e))
        return cb

    def hb_loop():
        iv = hb_interval()
        while not stop.wait(iv):
            wire.send({"kind": "hb", "inflight": inflight[0],
                       "served": served[0],
                       "mono": round(time.monotonic(), 3)})

    def read_loop():
        for line in sys.stdin:
            if stop.is_set():
                break
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            kind = msg.get("kind")
            if kind == "close":
                break
            if kind == "fetch_rows":
                # the gather leg's OWNER side: answer a version-pinned
                # row fetch from the host mirror, right here on the
                # reader thread (host copy, no device work)
                _answer_fetch(server, wire, replica, msg)
                continue
            if kind == "rows":
                # the gather leg's REQUESTER side: a relayed answer
                # for one of OUR in-flight fetches — deliver it to the
                # blocked gather call
                client = getattr(getattr(server, "pred", None),
                                 "_gather_client", None)
                if client is not None:
                    client.on_rows(msg)
                continue
            req_id = msg.get("id")
            if kind not in ("req", "fetch_rows", "rows"):
                # explicit unknown-kind rejection: a typo'd or
                # future kind must fail LOUD, not be silently
                # treated as a request (the wire-vocabulary bug
                # class roc-lint level eight audits for)
                emit("serve",
                     f"replica {replica}: rejecting unknown wire "
                     f"kind {kind!r}", console=False,
                     kind_rejected=str(kind), replica=replica)
                if req_id is not None:
                    wire.send({"kind": "res", "id": req_id,
                               "ok": False, "error": "ServeError",
                               "msg": f"unknown wire kind {kind!r}",
                               "retryable": False})
                continue
            if req_id is None:
                continue
            inflight[0] += 1
            fut = server.submit(msg.get("ids") or [],
                                deadline_ms=msg.get("deadline_ms"),
                                rid=msg.get("rid"))
            fut.add_done_callback(on_done(req_id))
        stop.set()

    hb = threading.Thread(target=hb_loop, name="replica:hb",
                          daemon=True)
    reader = threading.Thread(target=read_loop, name="replica:stdin",
                              daemon=True)
    hb.start()
    reader.start()
    # the main thread owns the lifecycle: SIGTERM (preemption guard
    # flag) or reader exit (EOF / close message) both funnel into ONE
    # drain path — readline retries EINTR (PEP 475), so the signal
    # can only be acted on from a poll loop like this
    while not stop.wait(0.05):
        if preempt.requested():
            stop.set()
    clean = server.drain(timeout=drain_timeout_s)
    hb.join(timeout=2.0)
    wire.send({"kind": "drained", "clean": bool(clean),
               "replica": replica, "served": served[0]})
    return clean


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m roc_tpu.serve.replica", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("artifact", help="exported serving artifact dir")
    ap.add_argument("--replica", type=int, default=0,
                    help="router-assigned replica index (the :proc "
                         "arm of serve fault drills)")
    ap.add_argument("--shard", default=None,
                    help="lo:hi node range this replica ADVERTISES "
                         "(routing metadata only; --shard-index is "
                         "the real sliced-table load)")
    ap.add_argument("--shard-index", type=int, default=None,
                    help="cold-load table slice K of a sharded "
                         "artifact (export --shards N): O(V/N)+halo "
                         "table bytes, foreign ids served through the "
                         "cross-shard gather leg")
    ap.add_argument("--table-budget-bytes", type=int, default=0,
                    help="per-replica serving-table byte cap: REFUSE "
                         "to serve (exit 3) when the loaded table "
                         "exceeds it — the capacity-proof enforcement "
                         "that makes 'the full table does not fit one "
                         "replica' a checkable fact, not a claim")
    ap.add_argument("--max-wait-ms", type=float, default=0.2)
    ap.add_argument("--max-queue", type=int, default=None)
    ap.add_argument("--drain-timeout", type=float, default=30.0)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    from ..obs.events import set_clock_identity
    from ..resilience import inject, preempt
    # identity FIRST: the fault arm and every event this process emits
    # (its timeline lane included) carry the replica index
    inject.note_proc_index(args.replica)
    set_clock_identity(proc=args.replica)
    preempt.install()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..obs.heartbeat import Heartbeat
    from ..utils.compile_cache import enable_compile_cache
    from .export import load_predictor
    from .server import DEFAULT_MAX_QUEUE, Server
    enable_compile_cache()
    with Heartbeat(f"replica{args.replica} loading artifact"):
        pred = load_predictor(args.artifact, shard=args.shard_index)
    table_bytes = int(pred.table_bytes())
    if args.table_budget_bytes and table_bytes > args.table_budget_bytes:
        # the capacity enforcement: an oversize table must refuse
        # LOUDLY before ready, never silently eat fleet memory — the
        # micro_serve capacity scenario proves a full-table load
        # trips this while the sliced loads fit
        from ..obs.events import emit
        emit("serve", f"replica {args.replica}: table "
             f"{table_bytes} B exceeds --table-budget-bytes "
             f"{args.table_budget_bytes} — refusing to serve",
             kind="table_budget_refused", replica=args.replica,
             table_bytes=table_bytes,
             budget=args.table_budget_bytes)
        print(f"error: serving table {table_bytes} B exceeds the "
              f"per-replica budget {args.table_budget_bytes} B "
              f"(export with --shards to slice it)", file=sys.stderr)
        return 3
    shard = None
    if pred.shard is not None:
        shard = [int(pred.shard[0]), int(pred.shard[1])]
    elif args.shard:
        lo, hi = args.shard.split(":")
        shard = [int(lo), int(hi)]
    wire = _Wire(sys.stdout)
    if pred.shard is not None:
        # wire the gather leg: the shard plan comes from this
        # replica's own loaded manifest, so it addresses owners by
        # range without any extra discovery round
        from .export import MANIFEST_NAME
        with open(os.path.join(args.artifact, MANIFEST_NAME)) as f:
            plan = (json.load(f).get("shards") or {}).get("plan") or []
        client = _GatherClient(wire, plan, pred.quant, args.replica)
        pred._gather_client = client
        pred.gather_fn = client.gather
    server = Server(
        pred, max_wait_ms=args.max_wait_ms,
        name=f"replica{args.replica}",
        max_queue=(DEFAULT_MAX_QUEUE if args.max_queue is None
                   else args.max_queue))
    wire.send({"kind": "ready", "replica": args.replica,
               "pid": os.getpid(),
               "num_nodes": int(pred.num_nodes),
               "num_classes": pred.num_classes,
               "buckets": list(pred.buckets),
               "backend": pred.backend, "shard": shard,
               "quant": pred.quant,
               "table_version": int(pred.published().version),
               "table_bytes": table_bytes})
    serve_loop(server, wire, args.replica,
               drain_timeout_s=args.drain_timeout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
