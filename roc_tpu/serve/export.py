"""Exported predictor artifacts: ``python -m roc_tpu.export``.

The export step is where serving's cold-start cost is paid, once,
off the request path:

1. resolve the model + config through the SAME
   ``train/trainer.resolve_config`` pass training uses (fuse rewrite,
   impl auto-resolution, attention policy) — the artifact records the
   RESOLVED state, so a server can never re-resolve differently;
2. for the fixed-propagation family, materialize the propagation
   table (``serve/propagation.py`` — streamed through the
   ``StagingPool`` machinery, so >HBM graphs export the way they
   train);
3. AOT-compile every bucketed serve program into the persistent
   compile cache (``utils/prewarm.warm_candidates`` — the same
   warm-vs-cold accounting the bench children record) and assert
   warm-hit parity with a second pass;
4. write ``serve_manifest.json`` — program keys, quantized buckets,
   the resolved model op list (``Model.to_spec``), and the model
   fingerprint reusing checkpoint v2's strict half
   (``utils/checkpoint.params_signature``) — next to ``params.npz``
   and ``propagation.npz``.

A cold server process (``load_predictor`` + ``serve/server.py``) then
reaches first-query readiness with ZERO new compiles: its programs
are keyed identically to the export-time warm set (asserted in
tests/test_serve.py).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.events import emit
from .predictor import SERVE_BUCKETS, Predictor, ShardSlice
from .propagation import (PropagationCache, logits_table_cache,
                          prefix_descriptors)

MANIFEST_NAME = "serve_manifest.json"
MANIFEST_VERSION = 1

SHARD_FILE = "propagation_shard{k}.npz"


def _host_params(params) -> Dict[str, np.ndarray]:
    import jax
    # export-time persistence fetch, not a request-path sync
    return {k: np.asarray(jax.device_get(v))  # roc-lint: ok=host-sync-hot-path
            for k, v in params.items()}


def resolve_backend(model, backend: str) -> Tuple[str, Optional[str]]:
    """``(backend, flavor)``: 'auto' picks 'precomputed' (flavor
    'akx') when the model has a parameter-free propagation prefix
    (``Model.precompute_split`` — the SGC family), else 'full'.  An
    explicit 'precomputed' on a model without the split serves the
    frozen full-forward logits instead (flavor 'table' — the
    decoupled APPNP shape)."""
    has_split = model.precompute_split() is not None
    if backend == "auto":
        return (("precomputed", "akx") if has_split else ("full", None))
    if backend == "precomputed":
        return ("precomputed", "akx" if has_split else "table")
    if backend == "full":
        return ("full", None)
    raise ValueError(f"unknown serve backend {backend!r}; expected "
                     "'auto', 'precomputed', or 'full'")


def _full_gctx(model, dataset, config):
    from ..train.trainer import make_graph_context
    return make_graph_context(
        dataset, config.aggr_impl, config.chunk,
        symmetric=config.symmetric,
        sect_sub_w=config.sect_sub_w, sect_u16=config.sect_u16,
        bdense_min_fill=config.bdense_min_fill,
        bdense_a_budget=config.bdense_a_budget,
        bdense_group=config.bdense_group,
        verbose=config.verbose,
        fuse=model.num_fused_aggregates() > 0,
        head_chunk=0)


def _num_classes(model) -> Optional[int]:
    dims = [op.dim for op in model._ops if op.kind == "linear"]
    return dims[-1] if dims else None


def _full_logits_host(model, dataset, config, params) -> np.ndarray:
    """The frozen full-forward logits — the 'table' flavor's
    precompute.  Runs the eval forward ONCE at export (this program is
    export-time-only; it is deliberately not part of the audited serve
    set)."""
    import jax
    import jax.numpy as jnp

    from ..train.trainer import cast_floats, compute_dtype_of
    gctx = _full_gctx(model, dataset, config)
    compute = compute_dtype_of(config)
    feats = jnp.asarray(dataset.features, dtype=compute)

    logits = jax.jit(
        lambda p, f, g: model.apply(cast_floats(p, compute), f, g,
                                    key=None, train=False)
    )(params, feats, gctx)
    # export-time precompute fetch, not a request-path sync
    return np.asarray(jax.device_get(logits),  # roc-lint: ok=host-sync-hot-path
                      dtype=np.float32)


def build_predictor(model, dataset, config, params=None,
                    backend: str = "auto",
                    buckets: Sequence[int] = SERVE_BUCKETS,
                    cache: Optional[PropagationCache] = None,
                    quant: str = "off",
                    verbose: bool = False) -> Predictor:
    """Resolve + build a live Predictor.  ``params=None`` initializes
    fresh weights (rig/benchmark use); ``cache`` short-circuits the
    propagation precompute (the artifact loader passes the persisted
    one — live builds compute it here).  ``quant`` selects the serving
    table encoding (``serve/quant.py``); the drift GATE lives in
    :func:`export_predictor` — a live build is ungated rehearsal."""
    import jax

    from ..train.trainer import (resolve_config, resolve_symmetric)
    import dataclasses
    model, config, _ = resolve_config(model, dataset, config)
    config = dataclasses.replace(
        config, symmetric=resolve_symmetric(dataset, config.symmetric))
    if params is None:
        params = model.init_params(jax.random.PRNGKey(config.seed),
                                   dtype=config.dtype)
    backend, flavor = resolve_backend(model, backend)
    head_model = None
    gctx = None
    if backend == "precomputed":
        if flavor == "akx":
            prefix_ops, head_model = model.precompute_split()
            if cache is None:
                cache = PropagationCache.build(
                    dataset.graph, prefix_descriptors(prefix_ops),
                    np.asarray(dataset.features))
        elif cache is None:
            cache = logits_table_cache(
                _full_logits_host(model, dataset, config, params))
    else:
        gctx = _full_gctx(model, dataset, config)
    emit("serve", f"predictor: backend={backend}"
         + (f"/{flavor}" if flavor else "")
         + f" buckets={tuple(sorted(buckets))} V={dataset.graph.num_nodes}",
         console=verbose, kind="build", backend=backend, flavor=flavor)
    return Predictor(model, config, params, backend, buckets,
                     cache=cache, head_model=head_model, flavor=flavor,
                     dataset=dataset if backend == "full" else None,
                     gctx=gctx, num_classes=_num_classes(model),
                     quant=quant, verbose=verbose)


# ------------------------------------------------------- sharded slices

def make_shard_slices(cache: PropagationCache, num_shards: int,
                      buckets: Sequence[int],
                      quant: str = "off") -> List[ShardSlice]:
    """The export-time shard PLAN (PR 20): contiguous ``[lo, hi)``
    vertex ranges from the trainer's own edge-balanced sweep
    (``core/partition.edge_balanced_bounds`` — serve slices inherit
    training's partition law), under ONE fleet-uniform padded layout:
    ``rows_padded`` = max owned rows snapped to NODE_MULTIPLE, ``halo``
    = the largest serve bucket (a microbatch's foreign rows always
    fit).  Quantized slices are cut from the FULL table's ``(codes,
    scales)`` — per-row symmetric quantization is row-local, so slice
    codes are bit-identical to the unsharded artifact's — and every
    slice carries the full-table scale envelope so refresh guarding
    matches the export drift gate's measurement."""
    from ..core.partition import NODE_MULTIPLE, edge_balanced_bounds
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    V = cache.num_nodes
    plan: List[Tuple[int, int]] = []
    for left, right in edge_balanced_bounds(cache.row_ptr, num_shards):
        plan.append((int(left), int(right) + 1) if right >= left
                    else (V, V))
    own_max = max(hi - lo for lo, hi in plan)
    rows_padded = -(-max(own_max, 1)
                    // NODE_MULTIPLE) * NODE_MULTIPLE
    halo = max(int(b) for b in buckets)
    if quant != "off":
        from .quant import quantize_rows
        q, sc = quantize_rows(cache.table, quant)
        # host numpy scale max at EXPORT time, not a device fetch
        guard = float(sc.max())  # roc-lint: ok=host-sync-hot-path
        return [ShardSlice(lo, hi, V, rows_padded, halo,
                           codes=q[lo:hi], scales=sc[lo:hi],
                           scale_guard=guard) for lo, hi in plan]
    return [ShardSlice(lo, hi, V, rows_padded, halo,
                       rows=cache.table[lo:hi]) for lo, hi in plan]


def _write_shard_slice(out_dir: str, k: int, sl: ShardSlice,
                       quant: str) -> str:
    import tempfile
    data: Dict[str, Any] = {
        "lo": np.int64(sl.lo), "hi": np.int64(sl.hi),
        "num_nodes": np.int64(sl.num_nodes),
        "rows_padded": np.int64(sl.rows_padded),
        "halo": np.int64(sl.halo)}
    if quant != "off":
        from .quant import to_storage_bytes
        data["rows_q"] = to_storage_bytes(sl.codes)
        data["rows_scale"] = sl.scales
        data["scale_guard"] = np.float64(sl.scale_guard)
    else:
        data["rows"] = sl.rows
    path = os.path.join(out_dir, SHARD_FILE.format(k=k))
    fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **data)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_shard_slice(artifact_dir: str, k: int,
                     quant: str = "off") -> ShardSlice:
    """One persisted table slice → :class:`ShardSlice` (quantized
    slices rebuild codes from storage-byte views, bit-exact)."""
    path = os.path.join(artifact_dir, SHARD_FILE.format(k=k))
    with np.load(path) as z:
        lo, hi = int(z["lo"]), int(z["hi"])
        num_nodes = int(z["num_nodes"])
        rows_padded, halo = int(z["rows_padded"]), int(z["halo"])
        if quant != "off":
            from .quant import from_storage_bytes
            return ShardSlice(
                lo, hi, num_nodes, rows_padded, halo,
                codes=from_storage_bytes(z["rows_q"], quant),
                scales=np.asarray(z["rows_scale"], dtype=np.float32),
                # npz scalar at cold-load time, not a device fetch
                scale_guard=float(z["scale_guard"]))  # roc-lint: ok=host-sync-hot-path
        return ShardSlice(lo, hi, num_nodes, rows_padded, halo,
                          rows=np.asarray(z["rows"],
                                          dtype=np.float32))


def _shard_view_predictor(pred: Predictor,
                          sl: ShardSlice) -> Predictor:
    """A shard-view Predictor over the SAME resolved model/params —
    export warms its bucket programs once (one fleet-uniform table
    shape → one program set shared by every shard), and
    ``load_predictor(shard=k)`` rebuilds the identical keys."""
    return Predictor(pred.model, pred.config, pred.params,
                     "precomputed", pred.buckets, cache=None,
                     head_model=pred.head_model, flavor=pred.flavor,
                     num_classes=pred.num_classes, quant=pred.quant,
                     shard=sl, verbose=pred.verbose)


# ------------------------------------------------------------ artifact

def _quant_ref_logits(pred: Predictor, params, sample) -> np.ndarray:
    """The fp32 half of the drift gate: fp32 table rows + the
    UNquantized params through the same head.  Export-time-only
    program, deliberately outside the audited serve set (the
    ``_full_logits_host`` precedent)."""
    import jax
    import jax.numpy as jnp

    from ..train.trainer import cast_floats
    rows = pred.cache.table[sample]
    if pred.flavor == "table":
        return np.asarray(rows, dtype=np.float32)
    x = jnp.asarray(rows, dtype=pred.compute)
    out = jax.jit(
        lambda p, v, g: pred.head_model.apply(
            cast_floats(p, pred.compute), v, g, key=None, train=False)
    )(params, x, pred._gctx)
    # export-time gate fetch, not a request-path sync
    return np.asarray(jax.device_get(out),  # roc-lint: ok=host-sync-hot-path
                      dtype=np.float32)


def export_predictor(pred: Predictor, out_dir: str,
                     dataset_meta: Optional[Dict[str, Any]] = None,
                     cache_dir: Optional[str] = None,
                     verify_warm: bool = True,
                     drift_argmax_min: Optional[float] = None,
                     drift_dlogit_max: Optional[float] = None,
                     shards: int = 0
                     ) -> Dict[str, Any]:
    """Persist ``pred`` as a serving artifact and pre-pay its compile
    wall: params + propagation tables + manifest on disk, every bucket
    program AOT-compiled into the persistent cache.  With
    ``verify_warm`` a second AOT pass asserts every program is now a
    warm hit — the prewarm-parity guarantee the manifest's
    ``program_keys`` advertise.  Returns the manifest dict.

    A quantized predictor additionally runs the measured accuracy
    drift gate BEFORE any file is written: argmax agreement + max
    |Δlogit| vs the fp32 reference on a held-out node sample, with
    :class:`roc_tpu.serve.quant.QuantDriftError` refusal past the
    thresholds (CLI-adjustable; defaults in ``serve/quant.py``) —
    a drifting quantization never becomes an artifact."""
    from ..utils.checkpoint import params_signature
    import jax.numpy as jnp
    host_params = _host_params(pred.params)
    from .quant import QuantSpec
    qblock: Dict[str, Any] = {"spec": QuantSpec(pred.quant).to_json()}
    store_params = host_params
    if pred.quant != "off":
        from ..train.trainer import compute_dtype_of
        from .quant import (drift_report, drift_sample,
                            quantize_params, require_drift_ok,
                            row_scales, scale_stats)
        params_orig = pred.params
        store_params, roundtrip, qkeys = quantize_params(
            host_params, pred.quant)
        # the export-time predictor must serve the exact values a
        # cold load reconstructs: swap in the dequantize∘quantize
        # round trip (structural fingerprint unchanged)
        pred.params = {k: jnp.asarray(v)
                       for k, v in roundtrip.items()}
        sample = drift_sample(pred.num_nodes)
        drift = drift_report(
            _quant_ref_logits(pred, params_orig, sample),
            pred.query(sample),
            **{k: v for k, v in
               (("argmax_min", drift_argmax_min),
                ("dlogit_max", drift_dlogit_max)) if v is not None})
        qblock["drift"] = drift
        qblock["params"] = {"quantized": qkeys,
                            "scale_suffix": "::scale"}
        qblock["scale_stats"] = [scale_stats(row_scales(s, pred.quant))
                                 for s in pred.cache.stages]
        require_drift_ok(drift, f"export to {out_dir}")
    if pred.cache is not None:
        from .quant import table_bytes
        shapes = [s.shape for s in pred.cache.stages]
        b_fp32 = sum(table_bytes(s, "off") for s in shapes)
        b_mode = sum(table_bytes(s, pred.quant) for s in shapes)
        qblock["table"] = {
            "stages": len(shapes),
            "bytes_fp32": int(b_fp32),
            "bytes": int(b_mode),
            "shrink": round(b_fp32 / max(b_mode, 1), 2)}
    os.makedirs(out_dir, exist_ok=True)
    np.savez(os.path.join(out_dir, "params.npz"), **store_params)
    if pred.cache is not None:
        pred.cache.save(os.path.join(out_dir, "propagation.npz"),
                        quant=pred.quant)
    shard_block: Optional[Dict[str, Any]] = None
    if shards:
        # sliced artifacts (PR 20): per-shard table slices under one
        # fleet-uniform padded shape, warmed ONCE through a shard-view
        # predictor — every shard's cold load then hits the same
        # program set with zero new compiles
        if pred.backend != "precomputed" or pred.cache is None:
            raise ValueError("sharded export applies to the "
                             "precomputed table backend")
        from .quant import table_bytes
        slices = make_shard_slices(pred.cache, shards, pred.buckets,
                                   pred.quant)
        files = [os.path.basename(
            _write_shard_slice(out_dir, k, sl, pred.quant))
            for k, sl in enumerate(slices)]
        spred = _shard_view_predictor(pred, slices[0])
        swarm = spred.warm(cache_dir=cache_dir,
                           name="serve_export_shard")
        if swarm.get("failed"):
            raise RuntimeError(
                f"sharded export: {swarm['failed']} shard-view "
                f"program(s) failed to AOT-compile — a sliced cold "
                f"load would compile at first query")
        F = int(pred.cache.table.shape[1])
        shard_block = {
            "n": int(shards),
            "plan": [[int(sl.lo), int(sl.hi)] for sl in slices],
            "rows_padded": int(slices[0].rows_padded),
            "halo": int(slices[0].halo),
            "files": files,
            # the capacity math the fleet view / sentinel column reads:
            # per-replica bytes are O(V/N) + halo, vs O(V) full
            "bytes_per_replica": int(table_bytes(
                (slices[0].rows_padded + slices[0].halo + 1, F),
                pred.quant)),
            "bytes_full": int(table_bytes(
                (pred.num_nodes + 1, F), pred.quant)),
            "program_keys": spred.program_keys(),
            "prewarm": {k: swarm.get(k) for k in
                        ("programs", "compile_warm_hits",
                         "compile_cold", "failed", "prewarm_s",
                         "cache_unavailable")},
        }
    cfg = pred.config
    manifest: Dict[str, Any] = {
        "version": MANIFEST_VERSION,
        "backend": pred.backend,
        "flavor": pred.flavor,
        "buckets": list(pred.buckets),
        "model": pred.model.to_spec(),
        "num_classes": pred.num_classes,
        "config": {
            "dtype": str(jnp.dtype(cfg.dtype)),
            "compute_dtype": (None if cfg.compute_dtype is None
                              else str(jnp.dtype(cfg.compute_dtype))),
            "aggr_impl": cfg.aggr_impl, "chunk": cfg.chunk,
            "symmetric": bool(cfg.symmetric),
            "sect_sub_w": cfg.sect_sub_w, "sect_u16": cfg.sect_u16,
            "bdense_min_fill": cfg.bdense_min_fill,
            "bdense_a_budget": cfg.bdense_a_budget,
            "bdense_group": cfg.bdense_group,
        },
        # checkpoint v2's strict half, reused verbatim: a server can
        # hold an artifact against the checkpoint lineage it claims
        "fingerprint": {
            "params_sig": params_signature(host_params),
            "dtype": str(jnp.dtype(cfg.dtype)),
            "compute_dtype": (None if cfg.compute_dtype is None
                              else str(jnp.dtype(cfg.compute_dtype))),
            "dataset": dict(dataset_meta or {}),
        },
        "dataset": dict(dataset_meta or {}),
        "num_nodes": pred.num_nodes,
        "program_keys": pred.program_keys(),
        "quant": qblock,
        "shards": shard_block,
        "created": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    warm = pred.warm(cache_dir=cache_dir, name="serve_export")
    manifest["prewarm"] = {k: warm.get(k) for k in
                          ("programs", "compile_warm_hits",
                           "compile_cold", "failed", "prewarm_s",
                           "cache_unavailable")}
    if warm.get("failed"):
        raise RuntimeError(
            f"serve export: {warm['failed']} program(s) failed to "
            f"AOT-compile — the artifact would cold-compile at first "
            f"query; see the compile events")
    if verify_warm and not warm.get("cache_unavailable"):
        check = pred.warm(cache_dir=cache_dir, name="serve_verify")
        manifest["prewarm"]["verified_warm_hits"] = \
            check.get("compile_warm_hits")
        if check.get("compile_warm_hits") != check.get("programs"):
            raise RuntimeError(
                f"serve export warm-hit parity FAILED: "
                f"{check.get('compile_warm_hits')} of "
                f"{check.get('programs')} programs warm on the second "
                f"pass — the persistent cache is not serving the "
                f"programs just compiled (unstable cache key?)")
    path = os.path.join(out_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    emit("serve", f"artifact exported to {out_dir}: {pred.backend}"
         + (f"/{pred.flavor}" if pred.flavor else "")
         + f", {len(manifest['program_keys'])} programs "
         f"({manifest['prewarm']['compile_warm_hits']} warm/"
         f"{manifest['prewarm']['compile_cold']} cold)",
         kind="export", path=out_dir, backend=pred.backend)
    return manifest


def export_trainer(trainer, dataset, out_dir: str,
                   backend: str = "auto",
                   buckets: Sequence[int] = SERVE_BUCKETS,
                   cache_dir: Optional[str] = None,
                   verify_warm: bool = True,
                   quant: str = "off") -> Dict[str, Any]:
    """Export a LIVE trainer's weights as a serving artifact — works
    for both ``Trainer`` and ``DistributedTrainer`` (replicated params
    fetch identically); the trainer's model/config are already
    resolved, and ``resolve_config`` is idempotent, so the artifact
    records exactly what trained."""
    pred = build_predictor(
        trainer.model, dataset, trainer.config,
        params=trainer.params, backend=backend, buckets=buckets,
        quant=quant)
    meta = {"V": int(dataset.graph.num_nodes),
            "E": int(dataset.graph.num_edges),
            "name": getattr(dataset, "name", None)}
    return export_predictor(pred, out_dir, dataset_meta=meta,
                            cache_dir=cache_dir,
                            verify_warm=verify_warm)


def load_predictor(artifact_dir: str, dataset=None,
                   verbose: bool = False,
                   shard: Optional[int] = None) -> Predictor:
    """Rebuild a Predictor from an exported artifact — the cold-server
    path.  No resolve pass runs here: the manifest carries the
    RESOLVED model op list and config fields, so the programs built
    are keyed identically to the export-time warm set.  ``dataset`` is
    required for the full-graph backend only (precomputed artifacts
    are self-contained).

    ``shard=k`` cold-loads ONE table slice of a sharded artifact
    (``export --shards N``): O(V/N)+halo table bytes instead of O(V),
    same global id space, program keys identical to the export-time
    shard-view warm set (zero new compiles on any shard) — ids the
    slice does not own are served through the cross-shard gather leg
    once the caller wires ``pred.gather_fn``."""
    import jax.numpy as jnp

    from ..models.builder import Model
    from ..train.trainer import TrainConfig
    from ..utils.checkpoint import params_signature
    with open(os.path.join(artifact_dir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    if manifest.get("version") != MANIFEST_VERSION:
        raise ValueError(
            f"{artifact_dir}: manifest version "
            f"{manifest.get('version')} != {MANIFEST_VERSION}")
    model = Model.from_spec(manifest["model"])
    mc = manifest["config"]
    config = TrainConfig(
        verbose=verbose, memory="manual", aggr_fuse="off",
        dtype=jnp.dtype(mc["dtype"]),
        compute_dtype=(None if mc["compute_dtype"] is None
                       else jnp.dtype(mc["compute_dtype"])),
        aggr_impl=mc["aggr_impl"], chunk=mc["chunk"],
        symmetric=mc["symmetric"], sect_sub_w=mc["sect_sub_w"],
        sect_u16=mc["sect_u16"],
        bdense_min_fill=mc["bdense_min_fill"],
        bdense_a_budget=mc["bdense_a_budget"],
        bdense_group=mc["bdense_group"])
    qmode = ((manifest.get("quant") or {}).get("spec")
             or {}).get("mode", "off")
    with np.load(os.path.join(artifact_dir, "params.npz")) as z:
        raw = {k: np.asarray(z[k]) for k in z.files}
    if qmode != "off":
        # storage-byte views + ::scale companions → fp32, then cast
        # like any params load; the fingerprint is structural, so the
        # reconstructed tree hashes identically to the exported one
        from .quant import dequantize_params
        raw = dequantize_params(raw, qmode)
    params = {k: jnp.asarray(v, dtype=config.dtype)
              for k, v in raw.items()}
    sig = params_signature(params)
    want = (manifest.get("fingerprint") or {}).get("params_sig")
    if want and sig != want:
        raise ValueError(
            f"{artifact_dir}: params fingerprint mismatch ({sig} != "
            f"manifest {want}) — params.npz does not belong to this "
            f"manifest")
    backend, flavor = manifest["backend"], manifest.get("flavor")
    cache = None
    head_model = None
    gctx = None
    slice_ = None
    if shard is not None:
        sb = manifest.get("shards")
        if not sb:
            raise ValueError(
                f"{artifact_dir}: shard={shard} requested but the "
                f"artifact was not exported with --shards")
        if not (0 <= int(shard) < int(sb["n"])):
            raise ValueError(
                f"{artifact_dir}: shard {shard} out of range "
                f"[0, {sb['n']})")
        slice_ = load_shard_slice(artifact_dir, int(shard), qmode)
        if flavor == "akx":
            head_model = model.precompute_split()[1]
    elif backend == "precomputed":
        cache = PropagationCache.load(
            os.path.join(artifact_dir, "propagation.npz"))
        if flavor == "akx":
            head_model = model.precompute_split()[1]
    else:
        if dataset is None:
            raise ValueError(
                "full-graph serving needs the dataset (the graph is "
                "not part of the artifact); pass dataset=")
        want_v = int(manifest["num_nodes"])
        want_e = (manifest.get("dataset") or {}).get("E")
        if int(dataset.graph.num_nodes) != want_v or (
                want_e is not None
                and int(dataset.graph.num_edges) != int(want_e)):
            raise ValueError(
                f"dataset V={dataset.graph.num_nodes}/"
                f"E={dataset.graph.num_edges} != artifact "
                f"V={want_v}/E={want_e} — full-graph serving on a "
                f"different graph than the export would be silently "
                f"wrong")
        gctx = _full_gctx(model, dataset, config)
    pred = Predictor(model, config, params, backend,
                     manifest["buckets"], cache=cache,
                     head_model=head_model, flavor=flavor,
                     dataset=dataset if backend == "full" else None,
                     gctx=gctx,
                     num_classes=manifest.get("num_classes"),
                     quant=qmode, shard=slice_, verbose=verbose)
    # a sliced load's programs must match the export-time SHARD-VIEW
    # warm set (one fleet-uniform table shape → one key set shared by
    # every shard); full loads match the top-level keys
    want_keys = (manifest["shards"]["program_keys"]
                 if shard is not None
                 else manifest.get("program_keys"))
    live = pred.program_keys()
    if sorted(want_keys or []) != live:
        raise ValueError(
            f"{artifact_dir}: rebuilt program keys differ from the "
            f"manifest — this server would cold-compile; re-export "
            f"(manifest {len(want_keys or [])} vs "
            f"live {len(live)})")
    return pred


# ----------------------------------------------------------------- CLI

def parse_args(argv: Optional[List[str]] = None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m roc_tpu.export", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", required=True,
                    help="artifact directory (created)")
    ap.add_argument("--checkpoint", default=None,
                    help="training checkpoint (v3 directory or "
                         "legacy .npz) to export; "
                         "omitted = fresh Glorot weights (latency "
                         "rehearsal only — the export says so loudly)")
    ap.add_argument("--backend", default="auto",
                    choices=["auto", "precomputed", "full"],
                    help="'auto' = precomputed propagation for the "
                         "fixed-propagation family (SGC shape), full-"
                         "graph recompute otherwise")
    ap.add_argument("--buckets", default=None,
                    help="comma list of microbatch buckets (default "
                         f"{','.join(str(b) for b in SERVE_BUCKETS)})")
    ap.add_argument("--model", default="gcn",
                    choices=["gcn", "sage", "gin", "gat", "sgc",
                             "appnp", "gcn2"])
    ap.add_argument("-layers", default="16-16-4",
                    help="dash-separated dims (train/cli.py "
                         "convention)")
    ap.add_argument("--hops", type=int, default=None)
    ap.add_argument("--alpha", type=float, default=None)
    ap.add_argument("--lam", type=float, default=None)
    ap.add_argument("--heads", type=int, default=1)
    ap.add_argument("-dropout", type=float, default=0.5)
    ap.add_argument("-seed", type=int, default=1)
    ap.add_argument("-file", default=None, dest="file",
                    help="dataset prefix (default: the synthetic "
                         "smoke dataset, matching the training CLI)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "mixed"])
    ap.add_argument("--impl", default="auto")
    ap.add_argument("--fuse", default="auto",
                    choices=["auto", "on", "off"])
    ap.add_argument("--quantize", default="off",
                    choices=["off", "int8", "fp8"],
                    help="serving-table/params quantization "
                         "(symmetric per-row, scales alongside; int8 "
                         "is the portable floor, fp8-e4m3 where jax "
                         "supports it).  Export runs the accuracy "
                         "drift gate and REFUSES past the thresholds")
    ap.add_argument("--drift-argmax-min", type=float, default=None,
                    help="drift gate: minimum argmax agreement vs the "
                         "fp32 reference (default in serve/quant.py)")
    ap.add_argument("--drift-dlogit-max", type=float, default=None,
                    help="drift gate: maximum |Δlogit| vs the fp32 "
                         "reference (default in serve/quant.py)")
    ap.add_argument("--shards", type=int, default=0,
                    help="also write N per-shard propagation slices "
                         "+ a shard manifest block (edge-balanced "
                         "[lo,hi) plan, fleet-uniform padded shape); "
                         "a replica then cold-loads ONE slice "
                         "(load_predictor(shard=k)) at O(V/N)+halo "
                         "table bytes")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent compile cache dir (default: "
                         "$ROC_TPU_CACHE_DIR or ~/.cache/roc_tpu/xla)")
    ap.add_argument("--no-verify-warm", action="store_true",
                    help="skip the second AOT pass that asserts "
                         "warm-hit parity")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--events", default=None)
    ap.add_argument("-v", "--verbose", action="store_true")
    return ap.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> int:
    import sys
    args = parse_args(argv)
    if args.events:
        os.environ["ROC_TPU_EVENTS"] = args.events
        from ..obs.events import configure
        configure(jsonl_path=args.events)
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    layers = [int(x) for x in args.layers.split("-")]
    if len(layers) < 2:
        print("error: -layers needs at least in-dim and classes",
              file=sys.stderr)
        return 2
    from ..core.graph import load_dataset, synthetic_dataset
    from ..models import model_builders
    from ..train.trainer import TrainConfig, resolve_dtypes
    if args.file:
        ds = load_dataset(args.file, in_dim=layers[0],
                          num_classes=layers[-1])
    else:
        ds = synthetic_dataset(512, 8, in_dim=layers[0],
                               num_classes=layers[-1], seed=args.seed)
    kwargs: Dict[str, Any] = {}
    if args.model == "gat":
        kwargs["heads"] = args.heads
    if args.model in ("sgc", "appnp"):
        kwargs["k"] = (args.hops if args.hops is not None
                       else (2 if args.model == "sgc" else 10))
    if args.model in ("appnp", "gcn2"):
        kwargs["alpha"] = args.alpha if args.alpha is not None else 0.1
    if args.model == "gcn2":
        kwargs["lam"] = args.lam if args.lam is not None else 0.5
    model = model_builders()[args.model](
        layers, dropout_rate=args.dropout, **kwargs)
    dt, cdt = resolve_dtypes(args.dtype)
    config = TrainConfig(verbose=args.verbose, seed=args.seed,
                         aggr_impl=args.impl, aggr_fuse=args.fuse,
                         dtype=dt, compute_dtype=cdt)
    params = None
    if args.checkpoint:
        from ..utils.checkpoint import restore_params_only
        params, fp, epoch = restore_params_only(args.checkpoint)
        strict = (fp or {}).get("strict") or {}
        import jax.numpy as jnp
        if strict.get("dtype") and \
                strict["dtype"] != str(jnp.dtype(dt)):
            print(f"error: checkpoint dtype {strict['dtype']} != "
                  f"--dtype {jnp.dtype(dt)} — export with the "
                  f"training dtype", file=sys.stderr)
            return 2
        emit("serve", f"weights from {args.checkpoint} (epoch "
             f"{epoch})", kind="restore", epoch=epoch)
        params = {k: jnp_cast(v, dt) for k, v in params.items()}
    else:
        emit("serve", "no --checkpoint: exporting FRESH Glorot "
             "weights (latency rehearsal, not a trained model)",
             kind="fresh_params")
    buckets = (SERVE_BUCKETS if not args.buckets
               else tuple(int(b) for b in args.buckets.split(",")))
    pred = build_predictor(model, ds, config, params=params,
                           backend=args.backend, buckets=buckets,
                           quant=args.quantize, verbose=args.verbose)
    meta = {"V": int(ds.graph.num_nodes),
            "E": int(ds.graph.num_edges),
            "name": getattr(ds, "name", None),
            "prefix": args.file}
    manifest = export_predictor(pred, args.out, dataset_meta=meta,
                                cache_dir=args.cache_dir,
                                verify_warm=not args.no_verify_warm,
                                drift_argmax_min=args.drift_argmax_min,
                                drift_dlogit_max=args.drift_dlogit_max,
                                shards=args.shards)
    print(json.dumps({
        "artifact": args.out, "backend": manifest["backend"],
        "flavor": manifest["flavor"],
        "programs": len(manifest["program_keys"]),
        "buckets": manifest["buckets"],
        "quant": manifest["quant"],
        "shards": (None if not manifest.get("shards") else
                   {k: manifest["shards"][k] for k in
                    ("n", "plan", "bytes_per_replica",
                     "bytes_full")}),
        "prewarm": manifest["prewarm"]}))
    return 0


def jnp_cast(v, dtype):
    import jax.numpy as jnp
    return jnp.asarray(v, dtype=dtype)


if __name__ == "__main__":
    import sys
    sys.exit(main())
