"""Precomputed-propagation tables: ``S^k X`` materialized once, served
forever (until an edge changes).

The SGC family's propagation is parameter-free and fixed
(``models/sgc.py``: ``logits = softmax(S^k X W)``), so at serving time
the whole graph part of the model collapses to a lookup table: evaluate
the norm/aggregate prefix ONCE at export (through the existing
streamed machinery — ``core/streaming.aggregate_to_host`` stages
feature blocks via the ``StagingPool``, so a >HBM graph exports the
same way it trains), keep the per-op intermediates host-side, and
answer node queries with a row gather + the dense head.

:class:`PropagationCache` owns the tables AND the invalidation hook:
when a vertex's edges change, only the rows inside the changed
vertices' k-hop out-neighborhood can change — the cache walks the op
chain once, growing the affected row set at each aggregation hop and
recomputing exactly those rows from the stored previous-stage values
(norm ops are row-local; aggregations spread one hop).  An edge append
on a Reddit-scale k=2 SGC touches O(deg^2) rows, not O(V).

Symmetric graphs only (out-neighbors == in-neighbors, so the CSR
serves both directions) — the same invariant the training aggregation
backward already requires (``scattergather_kernel.cu:160-170``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs.events import emit

# the op-descriptor vocabulary (kind + the attrs that matter) — the
# serializable mirror of the builder _Op kinds stream_prefix_to_host
# accepts, persisted in the serving manifest
PREFIX_KINDS = ("indegree_norm", "scatter_gather", "fused_aggregate")


def prefix_descriptors(prefix_ops) -> List[Dict[str, Any]]:
    """Builder ``_Op`` list → JSON-serializable descriptors."""
    out = []
    for op in prefix_ops:
        if op.kind not in PREFIX_KINDS:
            raise NotImplementedError(
                f"non-propagation op {op.kind!r} in a precompute "
                f"prefix")
        d: Dict[str, Any] = {"kind": op.kind}
        if op.kind == "scatter_gather":
            d["aggr"] = op.attrs.get("aggr", "sum")
        if op.kind == "fused_aggregate":
            d["activation"] = op.attrs.get("activation", "none")
        out.append(d)
    return out


def _inv_sqrt_degree(in_degree: np.ndarray) -> np.ndarray:
    from ..ops.norm import inv_sqrt_degree_np
    return inv_sqrt_degree_np(in_degree)


class PropagationCache:
    """Host-resident propagation tables with incremental recompute.

    ``stages[i]`` holds the fp32 ``[V, F]`` value AFTER prefix op
    ``i`` (``stages[-1]`` is the serving table); ``x0`` is the raw
    feature matrix the chain starts from.  O(n_ops · V · F) host
    bytes — the price of exact incremental invalidation; a deployment
    that never mutates edges can drop everything but ``stages[-1]``
    (``table_only=True`` restores that footprint and turns
    :meth:`add_edges` into a loud error instead of silent staleness).
    """

    def __init__(self, row_ptr: np.ndarray, col_idx: np.ndarray,
                 ops: Sequence[Dict[str, Any]], x0: np.ndarray,
                 stages: List[np.ndarray]):
        self.row_ptr = np.asarray(row_ptr, dtype=np.int64)
        self.col_idx = np.asarray(col_idx, dtype=np.int32)
        self.ops = [dict(op) for op in ops]
        self.x0 = x0
        self.stages = stages
        self.inv_sqrt = _inv_sqrt_degree(np.diff(self.row_ptr))
        # mutation counter: bumps once per add_edges batch.  The
        # DEVICE-side version boundary (what in-flight queries pin to)
        # lives in Predictor.refresh_rows' atomic publish; this
        # counter lets artifacts/stats say which host-table mutation
        # generation a publish came from.
        self.version = 0
        # quant mode of the artifact this cache was loaded from (None
        # for built/fp32-loaded caches) — load_predictor reads it to
        # reconstruct the device table under the exported spec
        self.loaded_quant: Optional[str] = None

    # ------------------------------------------------------------ build

    @classmethod
    def build(cls, graph, ops: Sequence[Dict[str, Any]],
              feats: np.ndarray, block_rows: int = 65536,
              prefetch: int = 1,
              table_only: bool = False) -> "PropagationCache":
        """Evaluate the prefix over the whole graph through THE
        trainer's own precompute walk
        (``core/streaming.stream_prefix_to_host`` — feature blocks
        staged via the ``StagingPool``, so >HBM graphs export the way
        they train, and serve tables can never diverge numerically
        from the streamed tier's), capturing the per-op intermediates
        for incremental invalidation."""
        from ..core.streaming import stream_prefix_to_host
        x0 = np.asarray(feats, dtype=np.float32).copy()
        stages: List[np.ndarray] = []
        stream_prefix_to_host(graph, list(ops), x0,
                              block_rows=block_rows,
                              prefetch=prefetch, capture=stages)
        if not stages:
            raise ValueError("empty propagation prefix")
        if table_only:
            stages = [stages[-1]]
            x0 = np.zeros((0, 0), dtype=np.float32)
            ops = [{"kind": "opaque"}]
        return cls(graph.row_ptr, graph.col_idx, ops, x0, stages)

    @property
    def table(self) -> np.ndarray:
        """The serving table: the final prefix stage, fp32 [V, F]."""
        return self.stages[-1]

    @property
    def num_nodes(self) -> int:
        return int(self.row_ptr.shape[0] - 1)

    # ----------------------------------------------------- invalidation

    def _in_rows(self, r: int) -> np.ndarray:
        return self.col_idx[self.row_ptr[r]:self.row_ptr[r + 1]]

    def _neighbors_of(self, rows: np.ndarray) -> np.ndarray:
        """Union of the rows' neighborhoods (symmetric CSR, so in- and
        out-neighbors coincide)."""
        if rows.size == 0:
            return rows
        chunks = [self.col_idx[self.row_ptr[r]:self.row_ptr[r + 1]]
                  for r in rows]
        return np.unique(np.concatenate(chunks)) if chunks else rows

    def add_edges(self, src, dst) -> np.ndarray:
        """Append edges and incrementally recompute every stage row the
        change can reach; returns the final-stage rows that changed (the
        caller refreshes the device copy of exactly those rows —
        ``Predictor.refresh_rows``).  ``src``/``dst`` are parallel id
        arrays; symmetric graphs need BOTH directions listed (the same
        contract as the training loader's edge lists).  Exact: the
        recomputed rows equal a full rebuild on the mutated graph to
        fp32 roundoff (tests/test_serve.py parity)."""
        if len(self.ops) == 1 and self.ops[0].get("kind") == "opaque":
            raise NotImplementedError(
                "this cache was built table_only=True (or holds a "
                "full-logits table) — incremental invalidation needs "
                "the per-op stages; re-export the artifact instead")
        src = np.asarray(src, dtype=np.int32).ravel()
        dst = np.asarray(dst, dtype=np.int32).ravel()
        if src.shape != dst.shape:
            raise ValueError("src/dst length mismatch")
        V = self.num_nodes
        if src.size and (src.min() < 0 or src.max() >= V
                         or dst.min() < 0 or dst.max() >= V):
            raise ValueError(f"edge ids out of range [0, {V})")
        # CSR insert: new edge (s, d) lands in row d's slice.  One
        # O(E) rebuild per invalidation batch — control-plane cost,
        # amortized over every query until the next mutation.
        order = np.argsort(dst, kind="stable")
        s_sorted, d_sorted = src[order], dst[order]
        insert_at = self.row_ptr[d_sorted + 1]
        new_col = np.insert(self.col_idx, insert_at, s_sorted)
        counts = np.bincount(d_sorted, minlength=V).astype(np.int64)
        new_ptr = self.row_ptr + np.concatenate(
            ([0], np.cumsum(counts)))
        self.row_ptr, self.col_idx = new_ptr, new_col
        # degrees changed on the destination rows: their norm scaling
        # changes at EVERY norm stage, so they seed the affected set
        changed = np.unique(d_sorted)
        self.inv_sqrt = _inv_sqrt_degree(np.diff(self.row_ptr))
        deg = np.maximum(np.diff(self.row_ptr).astype(np.float32), 1.0)
        affected = changed
        prev_of = [self.x0] + self.stages[:-1]
        for i, op in enumerate(self.ops):
            prev, cur = prev_of[i], self.stages[i]
            kind = op["kind"]
            if kind == "indegree_norm":
                cur[affected] = (prev[affected]
                                 * self.inv_sqrt[affected, None])
            elif kind in ("scatter_gather", "fused_aggregate"):
                # one hop of spread: rows whose in-neighborhood
                # includes an affected row, plus the rows whose edge
                # set itself changed (already seeded in `affected`)
                affected = np.union1d(affected,
                                      self._neighbors_of(affected))
                if kind == "fused_aggregate":
                    # pre-scale only the source rows actually gathered
                    # (O(affected·deg), never O(V))
                    for r in affected:
                        nbr = self._in_rows(r)
                        cur[r] = (prev[nbr]
                                  * self.inv_sqrt[nbr, None]).sum(axis=0)
                else:
                    for r in affected:
                        cur[r] = prev[self._in_rows(r)].sum(axis=0)
                if kind == "fused_aggregate":
                    cur[affected] *= self.inv_sqrt[affected, None]
                    if op.get("activation", "none") != "none":
                        # plain assignment, NOT out= on a fancy index
                        # (that writes into a temporary copy and the
                        # stage would keep pre-relu values)
                        cur[affected] = np.maximum(cur[affected], 0.0)
                elif op.get("aggr", "sum") == "avg":
                    cur[affected] /= deg[affected, None]
            else:  # pragma: no cover - build() rejects unknown kinds
                raise NotImplementedError(kind)
        self.version += 1
        emit("serve", f"invalidate: {src.size} edge(s) appended, "
             f"{affected.size} table row(s) recomputed "
             f"({affected.size / max(V, 1):.2%} of V, host table "
             f"generation {self.version})", console=False,
             kind="invalidate", edges=int(src.size),
             rows=int(affected.size), version=self.version)
        return affected

    # ------------------------------------------------------ persistence

    def save(self, path: str, quant: str = "off") -> None:
        """Persist the cache; ``quant`` in ``("int8", "fp8")`` stores
        the stage tables quantized (``stage_{i}_q`` storage-byte views
        + ``stage_{i}_scale``, spec in the ``quant`` blob) — the ≥3×
        stage-bytes shrink on disk.  ``x0`` stays fp32 either way: it
        is the chain's seed and quantizing it would compound error
        through every stage, for a fraction of the total bytes."""
        import json
        import os
        import tempfile
        data: Dict[str, np.ndarray] = {
            "row_ptr": self.row_ptr, "col_idx": self.col_idx,
            "x0": self.x0,
            "ops": np.frombuffer(json.dumps(self.ops).encode(),
                                 dtype=np.uint8).copy()}
        if quant != "off":
            from .quant import (QuantSpec, check_mode, quantize_rows,
                                to_storage_bytes)
            check_mode(quant)
            for i, s in enumerate(self.stages):
                q, sc = quantize_rows(s, quant)
                data[f"stage_{i}_q"] = to_storage_bytes(q)
                data[f"stage_{i}_scale"] = sc
            data["quant"] = np.frombuffer(
                json.dumps(QuantSpec(quant).to_json()).encode(),
                dtype=np.uint8).copy()
        else:
            for i, s in enumerate(self.stages):
                data[f"stage_{i}"] = s
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **data)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    @classmethod
    def load(cls, path: str) -> "PropagationCache":
        """Rebuild from disk.  Quantized artifacts dequantize into the
        usual fp32 host stages (invalidation math stays exact and
        mode-blind); ``loaded_quant`` records the artifact's mode so
        ``load_predictor`` re-quantizes the DEVICE table under the same
        spec — by the round-trip identity that reproduces the exported
        ``(q, scale)`` bit-for-bit."""
        import json
        with np.load(path) as z:
            ops = json.loads(bytes(np.asarray(z["ops"])).decode())
            if "quant" in z.files:
                from .quant import (QuantSpec, dequantize_rows,
                                    from_storage_bytes)
                spec = QuantSpec.from_json(json.loads(
                    bytes(np.asarray(z["quant"])).decode()))
                n = sum(1 for k in z.files
                        if k.startswith("stage_") and k.endswith("_q"))
                stages = [dequantize_rows(
                    from_storage_bytes(z[f"stage_{i}_q"], spec.mode),
                    z[f"stage_{i}_scale"]) for i in range(n)]
                out = cls(z["row_ptr"], z["col_idx"], ops, z["x0"],
                          stages)
                out.loaded_quant = spec.mode
                return out
            stages = [z[f"stage_{i}"]
                      for i in range(sum(1 for k in z.files
                                         if k.startswith("stage_")))]
            return cls(z["row_ptr"], z["col_idx"], ops, z["x0"],
                       stages)


def logits_table_cache(table: np.ndarray) -> PropagationCache:
    """Wrap a precomputed full-logits table (the gather-only flavor
    serving the APPNP/decoupled family, where propagation runs AFTER
    the MLP and the frozen forward itself is the cacheable object) in
    the same container.  No stages, no graph — :meth:`add_edges`
    refuses with the re-export message."""
    # export-time host build of the cache container, not the serve
    # hot path (quantization happens at device upload)
    t = np.asarray(table, dtype=np.float32)  # roc-lint: ok=dequant-hot-path
    V = t.shape[0]
    return PropagationCache(
        np.zeros(V + 1, dtype=np.int64), np.zeros(0, dtype=np.int32),
        [{"kind": "opaque"}], np.zeros((0, 0), dtype=np.float32), [t])
