"""The serving runtime: bucketed, AOT-warmable predict programs.

A :class:`Predictor` owns frozen params plus ONE compiled program per
batch bucket — the finite, auditable program set the ISSUE's serving
tier is built around:

- ``backend='precomputed'`` (the SGC/APPNP fixed-propagation family):
  a device-resident propagation table (``serve/propagation.py``) and a
  per-bucket ``gather rows → dense head`` program — microsecond-scale
  per dispatch, no graph op anywhere on the request path.  Flavor
  ``akx`` carries ``S^k X`` + the dense head; flavor ``table`` carries
  the frozen full-forward logits (the decoupled APPNP shape, where
  propagation runs after the MLP) and the head degenerates to the
  gather itself.
- ``backend='full'``: the honest always-fresh path — every dispatch
  runs the full-graph forward (the same resolved aggregation layout
  the trainer used) and gathers the queried rows on device.  This is
  the baseline the ``benchmarks/micro_serve.py`` speedup is measured
  against, and the fallback for models whose propagation is not fixed.

Request batch sizes quantize to :data:`SERVE_BUCKETS` so the program
set stays finite — the program-space auditor enumerates exactly these
programs (``analysis/programspace.py`` rig ``sgc_serve``) and
``python -m roc_tpu.prewarm`` / the export step AOT-compile them into
the persistent cache, so a cold server process answers its first query
with ZERO new compiles (program-key parity asserted in
tests/test_serve.py).

Every program compiles through ``ObservedJit`` — serve compiles emit
the same ``compile`` events (program key, lower/compile seconds) the
training slots do, so the warm-start assertion is checkable from the
event stream alone.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, \
    Tuple

import numpy as np

from ..obs.events import emit
from .propagation import PropagationCache


class TableVersion(NamedTuple):
    """One atomically-published serving table: ``version`` is a
    monotonically increasing counter, ``table`` the device array every
    dispatch under this version gathers from (the propagation table
    for the precomputed backend, the feature matrix for full-graph).

    The version carries its OWN quantization spec: ``qmode`` says how
    ``table``'s rows are encoded (``"off"`` → fp32/compute-dtype
    values, ``scale`` is None; ``"int8"``/``"fp8"`` → ``table`` holds
    the quantized codes and ``scale`` the per-row fp32 scales the
    serve program dequantizes with in-register).  Dispatch selects the
    PROGRAM by the captured version's qmode, so a mid-rollout
    fp32→int8 swap (:meth:`Predictor.publish_quant`) is bit-exact per
    captured version — the model checker's ``quant-spec-pinned``
    invariant, live on the wire as the ``res.qmode`` field.

    Publishing a new version NEVER mutates the previous one: the new
    buffer is the old one with exactly the affected rows rewritten
    (``.at[rows].set`` — copy-on-write at the device boundary), so a
    microbatch that captured version ``k`` at batch-take finishes
    bit-exact on ``k``'s values while later batches see ``k+1``
    (tests/test_serve_robustness.py pins this with a concurrent
    stress over a live ``add_edges`` publish)."""
    version: int
    table: Any
    scale: Any = None
    qmode: str = "off"

# Quantized microbatch sizes — the ONLY ids shapes a server ever
# dispatches.  Quantization is what keeps the serve program set finite
# and auditable (same philosophy as core/partition.quantize_plan_
# shapes, but bucket sizes are request shapes, not partition shapes —
# the auditor's drift rule exempts them exactly like the streamed
# head's block variants).
SERVE_BUCKETS: Tuple[int, ...] = (1, 8, 64, 512)


class ShardSlice(NamedTuple):
    """One exported propagation-table SLICE (PR 20): the final-stage
    rows a shard owns, plus the fleet-uniform padded layout every
    shard shares.  ``rows_padded`` is max-over-shards owned rows
    rounded up to the partition NODE_MULTIPLE and ``halo`` the staging
    region for cross-shard gathered rows (= the largest serve bucket,
    so one microbatch's foreign rows always fit) — ONE table shape
    ``(rows_padded + halo + 1, F)`` across the fleet means ONE serve
    program set per (qmode, bucket), AOT-warmed once at export.

    ``rows`` carries fp32 values (qmode off) or None; quantized slices
    carry ``codes`` + per-row ``scales`` instead — per-row symmetric
    quantization is row-local, so sliced codes are bit-identical to
    the full table's.  ``scale_guard`` is the EXPORT-gated envelope
    (full-table max scale × slack), not the slice-local max, so
    refresh guarding matches the drift gate's measurement."""
    lo: int
    hi: int
    num_nodes: int
    rows_padded: int
    halo: int
    rows: Optional[np.ndarray] = None
    codes: Optional[np.ndarray] = None
    scales: Optional[np.ndarray] = None
    scale_guard: Optional[float] = None


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (the padded dispatch size); requests past
    the largest bucket split into largest-bucket chunks upstream."""
    for b in buckets:
        if n <= b:
            return b
    return max(buckets)


class Predictor:
    """Frozen-params query engine; see module docstring.

    Construct via :func:`roc_tpu.serve.export.build_predictor` (live
    objects) or :func:`roc_tpu.serve.export.load_predictor` (an
    exported artifact) — the two run the IDENTICAL build path, which
    is what makes export-time program keys and a cold server's
    programs provably the same set.
    """

    def __init__(self, model, config, params,
                 backend: str, buckets: Sequence[int],
                 cache: Optional[PropagationCache] = None,
                 head_model=None, flavor: Optional[str] = None,
                 dataset=None, gctx=None,
                 num_classes: Optional[int] = None,
                 quant: str = "off",
                 shard: Optional[ShardSlice] = None,
                 verbose: bool = False):
        import jax.numpy as jnp

        from ..train.trainer import compute_dtype_of
        self.model = model
        self.config = config
        self.params = params
        self.backend = backend
        self.flavor = flavor
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or any(b < 1 for b in self.buckets):
            raise ValueError(f"bad serve buckets {buckets!r}")
        self.compute = compute_dtype_of(config)
        self.cache = cache
        self.head_model = head_model
        self.verbose = verbose
        from .quant import check_mode
        self.quant = check_mode(quant)
        if self.quant != "off" and backend != "precomputed":
            raise ValueError(
                "quantized serving applies to the precomputed table "
                "backend only (the full-graph path has no table to "
                "shrink)")
        self._jits: Dict[Tuple[str, int], Any] = {}
        self.scale = None
        # sharded-serving surface (PR 20): None/unset on full-table
        # predictors; the Server reads these via getattr so the two
        # shapes share one dispatch path
        self.shard = None
        self.gather_fn = None
        self.last_gather_ms: Optional[float] = None
        if backend == "precomputed":
            if shard is not None:
                # table-SLICE serving: this predictor owns global ids
                # [lo, hi); every other id is fetched through
                # ``gather_fn`` at query time and staged into the halo
                # region of a batch-local table copy
                self.shard = (int(shard.lo), int(shard.hi))
                self.num_nodes = int(shard.num_nodes)
                self._rows_padded = int(shard.rows_padded)
                self.halo = int(shard.halo)
                self.pad_id = self._rows_padded + self.halo
                self.table, self.scale = self._device_table_shard(
                    shard)
                self._gctx = self._trivial_gctx()
            elif cache is None:
                raise ValueError("precomputed backend needs a "
                                 "PropagationCache (or a ShardSlice)")
            else:
                self.num_nodes = cache.num_nodes
                self.table, self.scale = self._device_table(self.quant)
                self.pad_id = self.num_nodes
                self._gctx = self._trivial_gctx()
        elif backend == "full":
            if dataset is None or gctx is None:
                raise ValueError("full backend needs dataset + gctx "
                                 "(full-graph serving needs the graph "
                                 "by definition)")
            self.num_nodes = dataset.graph.num_nodes
            self.feats = jnp.asarray(dataset.features,
                                     dtype=self.compute)
            self._gctx = gctx
            self.pad_id = 0   # any valid row; padded outputs discarded
        else:
            raise ValueError(f"unknown serve backend {backend!r}; "
                             f"expected 'precomputed' or 'full'")
        self.num_classes = num_classes
        # the versioned-table publish point: a single attribute swap
        # under the lock (readers take a consistent (version, table)
        # snapshot by reading the one attribute — tuple assignment is
        # atomic, the lock serializes WRITERS against each other)
        self._pub_lock = threading.Lock()
        self._published = (
            TableVersion(0, self.table, self.scale, self.quant)
            if backend == "precomputed"
            else TableVersion(0, self.feats))
        self._build_jits(self.quant)

    # ------------------------------------------------------- programs

    def _device_table(self, mode: str):
        """Upload the host propagation table under ``mode``: fp32 →
        the compute dtype; quantized → the ``(codes, scales)`` pair
        the dequant-in-register program gathers from.  A dummy zero
        row at index V absorbs padded ids (its logits are sliced off
        host-side); its scale is 1.0 so padded dequant stays exact
        zeros.  Also (re)pins the scale-envelope guard the
        invalidation path re-checks refreshed rows against."""
        import jax.numpy as jnp
        if mode == "off":
            t = np.concatenate(
                [self.cache.table,
                 np.zeros((1, self.cache.table.shape[1]), np.float32)])
            return jnp.asarray(t, dtype=self.compute), None
        from .quant import SCALE_GUARD_SLACK, quantize_rows
        q, sc = quantize_rows(self.cache.table, mode)
        # host numpy scale vector — build-time bookkeeping, no device
        self._scale_guard = float(sc.max()) * SCALE_GUARD_SLACK  # roc-lint: ok=host-sync-hot-path
        qpad = np.concatenate(
            [q, np.zeros((1, q.shape[1]), dtype=q.dtype)])
        spad = np.concatenate([sc, np.ones(1, np.float32)])
        return jnp.asarray(qpad), jnp.asarray(spad)

    def _device_table_shard(self, sl: ShardSlice):
        """Upload one table SLICE under the fleet-uniform padded
        layout: owned rows at ``[0, hi-lo)``, zeros through
        ``rows_padded`` (NODE_MULTIPLE rounding), ``halo`` staging
        slots for gathered foreign rows, and the pad row last — one
        shape for every shard, so the bucket programs AOT-warmed at
        export cold-load with zero new compiles on any shard.  Also
        keeps the slice's host mirror: :meth:`read_rows` (the gather
        OWNER side) answers from it without a device round trip."""
        import jax.numpy as jnp
        own = sl.hi - sl.lo
        n = self._rows_padded + self.halo + 1
        if self.quant == "off":
            if sl.rows is None:
                raise ValueError("fp32 shard slice carries no rows")
            self._host_rows = np.asarray(sl.rows, dtype=np.float32)
            t = np.zeros((n, self._host_rows.shape[1]), np.float32)
            t[:own] = self._host_rows
            return jnp.asarray(t, dtype=self.compute), None
        from .quant import SCALE_GUARD_SLACK
        if sl.codes is None or sl.scales is None:
            raise ValueError("quantized shard slice needs codes "
                             "+ scales")
        self._host_codes = np.asarray(sl.codes)
        self._host_scales = np.asarray(sl.scales, dtype=np.float32)
        guard = sl.scale_guard
        if guard is None and own:
            # fallback: the slice-local envelope (exports always
            # persist the full-table one)
            guard = float(self._host_scales.max())  # roc-lint: ok=host-sync-hot-path
        self._scale_guard = float(guard or 1.0) * SCALE_GUARD_SLACK
        q = np.zeros((n, self._host_codes.shape[1]),
                     dtype=self._host_codes.dtype)
        q[:own] = self._host_codes
        s = np.ones(n, np.float32)
        s[:own] = self._host_scales
        return jnp.asarray(q), jnp.asarray(s)

    def _trivial_gctx(self):
        """A graph-free context for the dense head: precompute_split
        guarantees no head op touches the graph, so every graph field
        is a stub (the one-element arrays keep the pytree shape
        stable across processes — part of the program key)."""
        import jax.numpy as jnp

        from ..models.builder import GraphContext
        return GraphContext(
            edge_src=jnp.zeros(1, jnp.int32),
            edge_dst=jnp.zeros(1, jnp.int32),
            in_degree=jnp.zeros(1, jnp.int32),
            num_rows=1, gathered_rows=1, aggr_impl="segment",
            symmetric=True)

    def _build_jits(self, mode: str) -> None:
        """One ObservedJit per (quant mode, bucket).  Modes get
        DISTINCT program slots (``_q8``/``_qf8`` suffixes) because
        they are distinct programs with distinct arg avals — the
        auditor ratchets the quantized set under its own rig
        (``sgc_serve_q8``) while the fp32 slots stay byte-identical,
        keeping ``sgc_serve`` at budget delta +0."""
        from ..obs.compile_watch import ObservedJit
        for b in self.buckets:
            self._jits[(mode, b)] = ObservedJit(
                self._serve_step, name=self._slot(b, mode),
                verbose=self.verbose)

    _QSUFFIX = {"off": "", "int8": "_q8", "fp8": "_qf8"}

    def _slot(self, bucket: int, mode: str = "off") -> str:
        tag = (f"precomputed_{self.flavor}"
               if self.backend == "precomputed" else "full")
        return f"serve_{tag}{self._QSUFFIX[mode]}:{bucket}"

    def _serve_step(self, *args):
        import jax.numpy as jnp

        from ..train.trainer import cast_floats
        if self.backend == "precomputed":
            if len(args) == 5:
                # quantized: gather the bucket's code rows + scales
                # and dequantize IN-REGISTER — [bucket, F] widens to
                # the compute dtype, the [V, F] table never does (the
                # dequant-hot-path lint rule holds serve/ to this)
                params, qtab, qscale, ids, gctx = args
                x = (jnp.take(qtab, ids, axis=0).astype(self.compute)
                     * jnp.take(qscale, ids)[:, None]
                     .astype(self.compute))
            else:
                params, table, ids, gctx = args
                x = jnp.take(table, ids, axis=0)
            if self.flavor == "table":
                return x
            return self.head_model.apply(
                cast_floats(params, self.compute), x, gctx,
                key=None, train=False)
        params, feats, ids, gctx = args
        logits = self.model.apply(cast_floats(params, self.compute),
                                  feats, gctx, key=None, train=False)
        return jnp.take(logits, ids, axis=0)

    def _args_for(self, ids, pub: Optional[TableVersion] = None):
        """The per-dispatch argument tuple — ONE construction shared
        by the live call path and the candidate enumeration, so the
        auditor/prewarm keys and the runtime programs cannot drift.
        ``pub`` pins a captured table version (the microbatch server
        captures one per batch); None reads the current publication.
        Versions only swap the table VALUES, never its shape/dtype —
        within one qmode the program key is version-independent, and
        across qmodes the captured version routes to ITS mode's
        program (the quant-spec-pinned invariant)."""
        if pub is None:
            pub = self._published
        if pub.qmode != "off":
            return (self.params, pub.table, pub.scale, ids,
                    self._gctx)
        return (self.params, pub.table, ids, self._gctx)

    def serve_candidates(self) -> List[Any]:
        """The exact serve program set, as prewarmable auditor
        candidates (``analysis/programspace.Candidate``) — one program
        per bucket.  ``observed=False``: bucket sizes are request
        shapes, not partition shapes (the cache-key-drift rule's
        head-block exemption applies verbatim), but the programs still
        count against the ``program_budget`` ratchet and the prewarm
        driver AOT-compiles every one."""
        import jax
        import jax.numpy as jnp

        from ..analysis.programspace import Candidate
        cands: List[Any] = []
        quant = self.quant != "off"
        # the quantized 5-tuple splits the table role into codes +
        # scales (both version-swapped data planes); ids/gctx keep
        # their off-mode roles so the replication auditor sees the
        # same sharing story
        roles = (("params", "data", "data", "other", "tables")
                 if quant else ("params", "data", "other", "tables"))
        for b in self.buckets:
            ids = jax.ShapeDtypeStruct((b,), jnp.dtype(jnp.int32))
            args = self._args_for(ids)
            jit = self._jits[(self.quant, b)]._jit
            cands.append(Candidate(
                slot=self._slot(b, self.quant), fn=jit, args=args,
                donate=(), observed=False, roles=roles,
                aot=lambda j=jit, a=args: j.lower(*a).compile()))
        return cands

    def warm(self, cache_dir: Optional[str] = None,
             name: str = "serve") -> Dict[str, Any]:
        """AOT-compile every bucket program against the persistent
        cache (the export step calls this, and a cold server may too —
        first-query readiness becomes a warm-hit report instead of a
        latency spike)."""
        from ..utils.compile_cache import enable_compile_cache
        from ..utils.prewarm import warm_candidates
        d = enable_compile_cache(cache_dir, min_compile_secs=0.0)
        return warm_candidates(self.serve_candidates(), d, config=name,
                               verbose=self.verbose)

    def program_keys(self) -> List[str]:
        from ..obs.compile_watch import program_key_of
        return sorted(program_key_of(c.slot, c.args, c.donate)
                      for c in self.serve_candidates())

    # --------------------------------------------------------- queries

    def table_bytes(self) -> int:
        """Device bytes of the CURRENT published serving table
        (codes + per-row scales when quantized) — what a replica
        advertises on ``ready`` and the per-replica byte budget is
        enforced against.  Sharded predictors report the slice's
        padded O(V/N)+halo footprint, full ones O(V)."""
        from .quant import table_bytes as _tb
        pub = self._published
        return int(_tb(tuple(int(d) for d in pub.table.shape),
                       pub.qmode))

    def published(self) -> TableVersion:
        """A consistent snapshot of the current table version (one
        atomic attribute read).  Dispatch paths capture this ONCE per
        microbatch so every request in the batch is served from one
        version even while :meth:`invalidate` publishes a new one."""
        return self._published

    def query_device(self, ids_padded,
                     pub: Optional[TableVersion] = None):
        """One padded-bucket dispatch; returns the device logits
        ``[bucket, C]``.  ``ids_padded`` length must be a bucket."""
        b = int(ids_padded.shape[0])
        if pub is None:
            pub = self._published
        # the program is selected by the CAPTURED version's qmode —
        # a batch pinned to a fp32 version keeps running the fp32
        # program even after publish_quant lands int8 (quant-spec-
        # pinned, bit-exact per captured version)
        jit = self._jits.get((pub.qmode, b))
        if jit is None:
            raise ValueError(f"ids length {b} is not a bucket "
                             f"{self.buckets}")
        return jit(*self._args_for(ids_padded, pub))

    def query(self, node_ids,
              pub: Optional[TableVersion] = None) -> np.ndarray:
        """Synchronous convenience path: pad to the smallest fitting
        bucket, dispatch, fetch, slice.  The microbatch server
        (``serve/server.py``) is the production entry — it coalesces
        concurrent requests into one dispatch; this method is the
        single-caller form the parity tests pin.

        Sharded predictors accept the SAME global id space: ids this
        shard owns remap to local table rows; foreign ids are fetched
        through ``gather_fn`` (coalesced per chunk — one gather per
        microbatch, version-pinned to ``pub``) and staged into the
        halo slots of a batch-local table copy.  ``last_gather_ms``
        records the chunk-summed gather wall (None when every id was
        owned)."""
        import jax
        import jax.numpy as jnp
        ids = np.asarray(node_ids, dtype=np.int32).ravel()
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_nodes):
            raise ValueError(
                f"node ids out of range [0, {self.num_nodes})")
        if pub is None:
            pub = self.published()  # one version for every chunk
        self.last_gather_ms = None
        out: List[np.ndarray] = []
        cap = max(self.buckets)
        for lo in range(0, ids.size, cap):
            chunk = ids[lo:lo + cap]
            if self.shard is not None:
                chunk, pub_c = self._remap_chunk(chunk, pub)
            else:
                pub_c = pub
            b = bucket_for(chunk.size, self.buckets)
            padded = np.full(b, self.pad_id, dtype=np.int32)
            padded[:chunk.size] = chunk
            logits = self.query_device(jnp.asarray(padded), pub_c)
            # the result fetch IS this tier's product — the one
            # sanctioned host sync on the serve path
            got = jax.device_get(logits)  # roc-lint: ok=host-sync-hot-path
            out.append(np.asarray(got[:chunk.size], dtype=np.float32))
        return (np.concatenate(out) if out
                else np.zeros((0, self.num_classes or 0), np.float32))

    # ------------------------------------------- sharded tables (PR 20)

    def _remap_chunk(self, chunk: np.ndarray,
                     pub: TableVersion
                     ) -> Tuple[np.ndarray, TableVersion]:
        """Global chunk ids → local table rows.  Owned ids offset into
        ``[0, hi-lo)``; foreign ids are gathered (unique, one call)
        and remapped onto their staged halo slots."""
        lo, hi = self.shard
        local = chunk.astype(np.int64) - lo
        foreign = (chunk < lo) | (chunk >= hi)
        if foreign.any():
            uniq = np.unique(chunk[foreign])
            pub, slot_of = self._stage_foreign(uniq, pub)
            local[foreign] = [slot_of[int(g)] for g in chunk[foreign]]
        return local.astype(np.int32), pub

    def _stage_foreign(self, uniq: np.ndarray, pub: TableVersion
                       ) -> Tuple[TableVersion, Dict[int, int]]:
        """Fetch ``uniq`` foreign rows at exactly ``pub.version`` and
        stage them into the halo slots of a batch-local copy-on-write
        table (the published version is never mutated).  The gather is
        PINNED: an answer from any other version or qmode is retried
        once (the owner may be mid-publish) and then refused — the
        model checker's ``gather-version-pinned`` invariant, with the
        ``shard-gather`` seed showing what an unpinned gather mixes."""
        import time as _time

        import jax.numpy as jnp

        from .errors import GatherError
        if self.gather_fn is None:
            raise GatherError(
                f"shard [{self.shard[0]}, {self.shard[1]}) was asked "
                f"for {uniq.size} foreign row(s) but has no gather_fn "
                f"— sharded serving needs the cross-shard gather leg")
        if uniq.size > self.halo:
            raise GatherError(
                f"{uniq.size} unique foreign rows exceed the halo "
                f"staging region ({self.halo}); chunking must cap a "
                f"microbatch at the largest bucket")
        t0 = _time.perf_counter()
        vals, scales, ver, qmode = self.gather_fn(uniq, pub.version)
        if ver != pub.version or qmode != pub.qmode:
            vals, scales, ver, qmode = self.gather_fn(uniq,
                                                      pub.version)
        if ver != pub.version or qmode != pub.qmode:
            raise GatherError(
                f"gather pinned to v{pub.version}:{pub.qmode} was "
                f"answered from v{ver}:{qmode} twice — refusing to "
                f"mix table versions in one microbatch")
        slots = self._rows_padded + np.arange(uniq.size)
        idx = jnp.asarray(slots.astype(np.int32))
        if pub.qmode != "off":
            # quantized gathers ship the owner's stored CODES + per-row
            # scales verbatim — staging them is bit-exact by
            # construction (per-row symmetric quantization is row-local)
            table = pub.table.at[idx].set(
                jnp.asarray(np.asarray(vals),
                            dtype=pub.table.dtype))
            scale = pub.scale.at[idx].set(
                jnp.asarray(np.asarray(scales, dtype=np.float32)))
            staged = TableVersion(pub.version, table, scale,
                                  pub.qmode)
        else:
            table = pub.table.at[idx].set(
                jnp.asarray(np.asarray(vals, dtype=np.float32),
                            dtype=self.compute))
            staged = TableVersion(pub.version, table, None, "off")
        ms = (_time.perf_counter() - t0) * 1e3
        self.last_gather_ms = (self.last_gather_ms or 0.0) + ms
        slot_of = {int(g): int(s) for g, s in zip(uniq, slots)}
        return staged, slot_of

    # ---------------------------------------------------- invalidation

    def invalidate(self, src, dst) -> int:
        """Edge-append invalidation hook: incrementally recompute the
        k-hop neighborhood rows of the propagation table
        (``PropagationCache.add_edges``) and publish a NEW table
        version carrying exactly those rows (``refresh_rows``).
        Returns the number of rows refreshed.  Control-plane op — the
        scatter below compiles a tiny program per affected-set shape,
        deliberately OUTSIDE the audited serve set (mutations are
        rare; quantizing them would complicate the hot path for
        nothing).  Mutators serialize on the publish lock; query
        threads never block on it (they read the published snapshot)."""
        if self.backend != "precomputed" or self.cache is None:
            raise NotImplementedError(
                "invalidation needs the precomputed backend (full-"
                "graph serving recomputes every dispatch anyway)")
        with self._pub_lock:
            rows = self.cache.add_edges(src, dst)
            version = self._publish_rows_locked(rows)
        self._emit_publish(version, rows)
        return int(rows.size)

    def refresh_rows(self, rows: np.ndarray) -> None:
        """Publish a new table version with ``rows`` re-uploaded from
        the host cache.  The previous version's device buffer is left
        untouched — in-flight dispatches pinned to it finish
        bit-exact (``.at[rows].set`` materializes a fresh buffer:
        copy-on-write at the device boundary)."""
        if self.shard is not None:
            raise NotImplementedError(
                "sharded predictors have no full host cache — "
                "refreshes arrive as (rows, values) via apply_refresh")
        with self._pub_lock:
            version = self._publish_rows_locked(rows)
        self._emit_publish(version, rows)

    def read_rows(self, ids, version: int):
        """The gather OWNER side: raw stored rows for ``ids`` (which
        this predictor must own) at exactly table ``version``.
        Returns ``(values, scales, version, qmode)`` — fp32 rows with
        ``scales=None`` for qmode off, else stored codes + per-row
        scales, both host-side (sharded predictors answer from the
        slice's host mirror; full-table ones re-encode from the host
        cache, bit-identical per-row).  A version other than the live
        publication is refused — the requester's pin decides what to
        do (retry, then fail typed), never this side."""
        from .errors import GatherError
        if self.backend != "precomputed":
            raise GatherError("row fetches need the precomputed "
                              "table backend")
        pub = self._published
        if int(version) != pub.version:
            raise GatherError(
                f"row fetch pinned to v{version} refused: this "
                f"replica publishes v{pub.version}")
        ids = np.asarray(ids, dtype=np.int64).ravel()
        lo, hi = self.shard if self.shard is not None \
            else (0, self.num_nodes)
        if ids.size and (ids.min() < lo or ids.max() >= hi):
            raise GatherError(
                f"row fetch for ids outside owned range [{lo}, {hi})")
        local = ids - lo
        with self._pub_lock:
            if self.shard is not None:
                if pub.qmode != "off":
                    return (self._host_codes[local].copy(),
                            self._host_scales[local].copy(),
                            pub.version, pub.qmode)
                return (self._host_rows[local].copy(), None,
                        pub.version, pub.qmode)
            # the REQUESTED rows only (a gather is ≤ halo rows), from
            # the host cache — never a full fp32 table materialization
            vals = np.asarray(self.cache.table[local],  # roc-lint: ok=dequant-hot-path
                              dtype=np.float32)
        if pub.qmode != "off":
            from .quant import quantize_rows
            q, sc = quantize_rows(vals, pub.qmode)
            return q, sc, pub.version, pub.qmode
        return vals, None, pub.version, pub.qmode

    def apply_refresh(self, rows: np.ndarray,
                      values: np.ndarray) -> int:
        """Sharded half of the ``add_edges`` invalidation fan-out: the
        update originator (which holds the FULL PropagationCache) runs
        the k-hop recompute centrally and ships every shard the
        affected (global rows, fp32 values); each shard applies only
        the rows it OWNS and bumps its version either way — data
        lands on owning shards only, while version counters stay
        comparable across the fleet (an epoch-only bump on
        non-owners), which is what keeps cross-shard gathers pinnable
        mid-rollout.  Returns the number of rows this shard applied."""
        import jax.numpy as jnp
        if self.shard is None:
            raise NotImplementedError(
                "apply_refresh is the sharded refresh path; full-"
                "table predictors use invalidate()/refresh_rows()")
        rows = np.asarray(rows, dtype=np.int64).ravel()
        values = np.asarray(values, dtype=np.float32)
        lo, hi = self.shard
        mask = (rows >= lo) & (rows < hi)
        own = rows[mask] - lo
        vals = values[mask]
        with self._pub_lock:
            old = self._published
            version = old.version + 1
            if own.size == 0:
                # epoch-only bump: no owned data changed, but the
                # fleet-wide version counter must advance in lockstep
                self._published = TableVersion(
                    version, old.table, old.scale, old.qmode)
            elif old.qmode != "off":
                from .quant import QuantDriftError, quantize_rows
                q, sc = quantize_rows(vals, old.qmode)
                guard = getattr(self, "_scale_guard", None)
                smax = float(sc.max())  # roc-lint: ok=host-sync-hot-path
                if guard is not None and smax > guard:
                    raise QuantDriftError(
                        f"sharded refresh refused: row scale "
                        f"{smax:.6g} exceeds the gated envelope "
                        f"{guard:.6g}; serving stays on "
                        f"v{old.version}")
                self._host_codes[own] = q
                self._host_scales[own] = sc
                idx = jnp.asarray(own.astype(np.int32))
                table = old.table.at[idx].set(jnp.asarray(q))
                scale = old.scale.at[idx].set(jnp.asarray(sc))
                self.table, self.scale = table, scale
                self._published = TableVersion(version, table, scale,
                                               old.qmode)
            else:
                self._host_rows[own] = vals
                idx = jnp.asarray(own.astype(np.int32))
                table = old.table.at[idx].set(
                    jnp.asarray(vals, dtype=self.compute))
                self.table = table
                self._published = TableVersion(version, table, None,
                                               "off")
        self._emit_publish(version, own)
        return int(own.size)

    def _publish_rows_locked(self, rows: np.ndarray) -> Optional[int]:
        import jax.numpy as jnp
        if rows.size == 0:
            return None
        old = self._published
        idx = jnp.asarray(rows.astype(np.int32))
        if old.qmode != "off":
            # requantize ONLY the recomputed rows.  Per-row symmetric
            # scales are row-local, so these (q, scale) pairs are
            # bit-identical to quantizing a full rebuild of the
            # mutated table (tests/test_serve_quant.py pins it) —
            # incremental invalidation loses nothing to quantization.
            from .quant import QuantDriftError, quantize_rows
            q, sc = quantize_rows(self.cache.table[rows], old.qmode)
            guard = getattr(self, "_scale_guard", None)
            # host numpy scales (control-plane refresh, not a query)
            smax = float(sc.max())  # roc-lint: ok=host-sync-hot-path
            if guard is not None and smax > guard:
                # the post-invalidation drift re-check: a refreshed
                # row whose quantization step left the envelope the
                # export-time gate measured would serve coarser
                # values than anything validated — refuse BEFORE
                # publishing; the old version stays live and the
                # operator re-exports (re-gating) instead
                raise QuantDriftError(
                    f"invalidation refused: refreshed row scale "
                    f"{smax:.6g} exceeds the gated envelope "
                    f"{guard:.6g} (build max × slack); serving "
                    f"stays on v{old.version} — re-export to re-run "
                    f"the drift gate on the mutated graph")
            new_table = old.table.at[idx].set(jnp.asarray(q))
            new_scale = old.scale.at[idx].set(jnp.asarray(sc))
            self.table, self.scale = new_table, new_scale
            self._published = TableVersion(
                old.version + 1, new_table, new_scale, old.qmode)
            return old.version + 1
        vals = jnp.asarray(
            self.cache.table[rows].astype(np.float32),  # roc-lint: ok=dequant-hot-path
            dtype=self.compute)
        new_table = old.table.at[idx].set(vals)
        self.table = new_table
        self._published = TableVersion(
            old.version + 1, new_table, None, "off")
        return old.version + 1

    def publish_quant(self, mode: str) -> int:
        """Control-plane re-publication of the CURRENT host table
        under a new quant spec — the mid-rollout fp32→int8 (or back)
        swap.  The target mode's bucket programs are built before the
        publish so the hot path never constructs programs; the swap
        itself is one versioned publish, and in-flight batches pinned
        to the previous version finish on ITS mode's program against
        ITS buffers (quant-spec-pinned — the model checker's
        ``live-qmode`` seed shows what skipping the pin would serve).
        Returns the published version."""
        from .quant import check_mode
        if self.backend != "precomputed" or self.cache is None:
            raise NotImplementedError(
                "quant swaps apply to the precomputed table backend")
        mode = check_mode(mode)
        if (mode, self.buckets[0]) not in self._jits:
            self._build_jits(mode)
        with self._pub_lock:
            old = self._published
            table, scale = self._device_table(mode)
            self.table, self.scale = table, scale
            self.quant = mode
            version = old.version + 1
            self._published = TableVersion(version, table, scale,
                                           mode)
        emit("serve", f"table version {version} published "
             f"(quant swap {old.qmode}->{mode}; in-flight queries "
             f"finish on v{old.version}:{old.qmode})", console=False,
             kind="table_publish", version=version, rows=0,
             qmode=mode)
        return version

    def _emit_publish(self, version: Optional[int],
                      rows: np.ndarray) -> None:
        # after the publish lock is released: event I/O must never sit
        # on the mutation critical section (roc-lint
        # blocking-under-lock)
        if version is None:
            return
        emit("serve", f"table version {version} published "
             f"({rows.size} row(s) rewritten; in-flight queries "
             f"finish on v{version - 1})", console=False,
             kind="table_publish", version=version,
             rows=int(rows.size))
