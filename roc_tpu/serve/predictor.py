"""The serving runtime: bucketed, AOT-warmable predict programs.

A :class:`Predictor` owns frozen params plus ONE compiled program per
batch bucket — the finite, auditable program set the ISSUE's serving
tier is built around:

- ``backend='precomputed'`` (the SGC/APPNP fixed-propagation family):
  a device-resident propagation table (``serve/propagation.py``) and a
  per-bucket ``gather rows → dense head`` program — microsecond-scale
  per dispatch, no graph op anywhere on the request path.  Flavor
  ``akx`` carries ``S^k X`` + the dense head; flavor ``table`` carries
  the frozen full-forward logits (the decoupled APPNP shape, where
  propagation runs after the MLP) and the head degenerates to the
  gather itself.
- ``backend='full'``: the honest always-fresh path — every dispatch
  runs the full-graph forward (the same resolved aggregation layout
  the trainer used) and gathers the queried rows on device.  This is
  the baseline the ``benchmarks/micro_serve.py`` speedup is measured
  against, and the fallback for models whose propagation is not fixed.

Request batch sizes quantize to :data:`SERVE_BUCKETS` so the program
set stays finite — the program-space auditor enumerates exactly these
programs (``analysis/programspace.py`` rig ``sgc_serve``) and
``python -m roc_tpu.prewarm`` / the export step AOT-compile them into
the persistent cache, so a cold server process answers its first query
with ZERO new compiles (program-key parity asserted in
tests/test_serve.py).

Every program compiles through ``ObservedJit`` — serve compiles emit
the same ``compile`` events (program key, lower/compile seconds) the
training slots do, so the warm-start assertion is checkable from the
event stream alone.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, \
    Tuple

import numpy as np

from ..obs.events import emit
from .propagation import PropagationCache


class TableVersion(NamedTuple):
    """One atomically-published serving table: ``version`` is a
    monotonically increasing counter, ``table`` the device array every
    dispatch under this version gathers from (the propagation table
    for the precomputed backend, the feature matrix for full-graph).

    The version carries its OWN quantization spec: ``qmode`` says how
    ``table``'s rows are encoded (``"off"`` → fp32/compute-dtype
    values, ``scale`` is None; ``"int8"``/``"fp8"`` → ``table`` holds
    the quantized codes and ``scale`` the per-row fp32 scales the
    serve program dequantizes with in-register).  Dispatch selects the
    PROGRAM by the captured version's qmode, so a mid-rollout
    fp32→int8 swap (:meth:`Predictor.publish_quant`) is bit-exact per
    captured version — the model checker's ``quant-spec-pinned``
    invariant, live on the wire as the ``res.qmode`` field.

    Publishing a new version NEVER mutates the previous one: the new
    buffer is the old one with exactly the affected rows rewritten
    (``.at[rows].set`` — copy-on-write at the device boundary), so a
    microbatch that captured version ``k`` at batch-take finishes
    bit-exact on ``k``'s values while later batches see ``k+1``
    (tests/test_serve_robustness.py pins this with a concurrent
    stress over a live ``add_edges`` publish)."""
    version: int
    table: Any
    scale: Any = None
    qmode: str = "off"

# Quantized microbatch sizes — the ONLY ids shapes a server ever
# dispatches.  Quantization is what keeps the serve program set finite
# and auditable (same philosophy as core/partition.quantize_plan_
# shapes, but bucket sizes are request shapes, not partition shapes —
# the auditor's drift rule exempts them exactly like the streamed
# head's block variants).
SERVE_BUCKETS: Tuple[int, ...] = (1, 8, 64, 512)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (the padded dispatch size); requests past
    the largest bucket split into largest-bucket chunks upstream."""
    for b in buckets:
        if n <= b:
            return b
    return max(buckets)


class Predictor:
    """Frozen-params query engine; see module docstring.

    Construct via :func:`roc_tpu.serve.export.build_predictor` (live
    objects) or :func:`roc_tpu.serve.export.load_predictor` (an
    exported artifact) — the two run the IDENTICAL build path, which
    is what makes export-time program keys and a cold server's
    programs provably the same set.
    """

    def __init__(self, model, config, params,
                 backend: str, buckets: Sequence[int],
                 cache: Optional[PropagationCache] = None,
                 head_model=None, flavor: Optional[str] = None,
                 dataset=None, gctx=None,
                 num_classes: Optional[int] = None,
                 quant: str = "off",
                 verbose: bool = False):
        import jax.numpy as jnp

        from ..train.trainer import compute_dtype_of
        self.model = model
        self.config = config
        self.params = params
        self.backend = backend
        self.flavor = flavor
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or any(b < 1 for b in self.buckets):
            raise ValueError(f"bad serve buckets {buckets!r}")
        self.compute = compute_dtype_of(config)
        self.cache = cache
        self.head_model = head_model
        self.verbose = verbose
        from .quant import check_mode
        self.quant = check_mode(quant)
        if self.quant != "off" and backend != "precomputed":
            raise ValueError(
                "quantized serving applies to the precomputed table "
                "backend only (the full-graph path has no table to "
                "shrink)")
        self._jits: Dict[Tuple[str, int], Any] = {}
        self.scale = None
        if backend == "precomputed":
            if cache is None:
                raise ValueError("precomputed backend needs a "
                                 "PropagationCache")
            self.num_nodes = cache.num_nodes
            self.table, self.scale = self._device_table(self.quant)
            self.pad_id = self.num_nodes
            self._gctx = self._trivial_gctx()
        elif backend == "full":
            if dataset is None or gctx is None:
                raise ValueError("full backend needs dataset + gctx "
                                 "(full-graph serving needs the graph "
                                 "by definition)")
            self.num_nodes = dataset.graph.num_nodes
            self.feats = jnp.asarray(dataset.features,
                                     dtype=self.compute)
            self._gctx = gctx
            self.pad_id = 0   # any valid row; padded outputs discarded
        else:
            raise ValueError(f"unknown serve backend {backend!r}; "
                             f"expected 'precomputed' or 'full'")
        self.num_classes = num_classes
        # the versioned-table publish point: a single attribute swap
        # under the lock (readers take a consistent (version, table)
        # snapshot by reading the one attribute — tuple assignment is
        # atomic, the lock serializes WRITERS against each other)
        self._pub_lock = threading.Lock()
        self._published = (
            TableVersion(0, self.table, self.scale, self.quant)
            if backend == "precomputed"
            else TableVersion(0, self.feats))
        self._build_jits(self.quant)

    # ------------------------------------------------------- programs

    def _device_table(self, mode: str):
        """Upload the host propagation table under ``mode``: fp32 →
        the compute dtype; quantized → the ``(codes, scales)`` pair
        the dequant-in-register program gathers from.  A dummy zero
        row at index V absorbs padded ids (its logits are sliced off
        host-side); its scale is 1.0 so padded dequant stays exact
        zeros.  Also (re)pins the scale-envelope guard the
        invalidation path re-checks refreshed rows against."""
        import jax.numpy as jnp
        if mode == "off":
            t = np.concatenate(
                [self.cache.table,
                 np.zeros((1, self.cache.table.shape[1]), np.float32)])
            return jnp.asarray(t, dtype=self.compute), None
        from .quant import SCALE_GUARD_SLACK, quantize_rows
        q, sc = quantize_rows(self.cache.table, mode)
        # host numpy scale vector — build-time bookkeeping, no device
        self._scale_guard = float(sc.max()) * SCALE_GUARD_SLACK  # roc-lint: ok=host-sync-hot-path
        qpad = np.concatenate(
            [q, np.zeros((1, q.shape[1]), dtype=q.dtype)])
        spad = np.concatenate([sc, np.ones(1, np.float32)])
        return jnp.asarray(qpad), jnp.asarray(spad)

    def _trivial_gctx(self):
        """A graph-free context for the dense head: precompute_split
        guarantees no head op touches the graph, so every graph field
        is a stub (the one-element arrays keep the pytree shape
        stable across processes — part of the program key)."""
        import jax.numpy as jnp

        from ..models.builder import GraphContext
        return GraphContext(
            edge_src=jnp.zeros(1, jnp.int32),
            edge_dst=jnp.zeros(1, jnp.int32),
            in_degree=jnp.zeros(1, jnp.int32),
            num_rows=1, gathered_rows=1, aggr_impl="segment",
            symmetric=True)

    def _build_jits(self, mode: str) -> None:
        """One ObservedJit per (quant mode, bucket).  Modes get
        DISTINCT program slots (``_q8``/``_qf8`` suffixes) because
        they are distinct programs with distinct arg avals — the
        auditor ratchets the quantized set under its own rig
        (``sgc_serve_q8``) while the fp32 slots stay byte-identical,
        keeping ``sgc_serve`` at budget delta +0."""
        from ..obs.compile_watch import ObservedJit
        for b in self.buckets:
            self._jits[(mode, b)] = ObservedJit(
                self._serve_step, name=self._slot(b, mode),
                verbose=self.verbose)

    _QSUFFIX = {"off": "", "int8": "_q8", "fp8": "_qf8"}

    def _slot(self, bucket: int, mode: str = "off") -> str:
        tag = (f"precomputed_{self.flavor}"
               if self.backend == "precomputed" else "full")
        return f"serve_{tag}{self._QSUFFIX[mode]}:{bucket}"

    def _serve_step(self, *args):
        import jax.numpy as jnp

        from ..train.trainer import cast_floats
        if self.backend == "precomputed":
            if len(args) == 5:
                # quantized: gather the bucket's code rows + scales
                # and dequantize IN-REGISTER — [bucket, F] widens to
                # the compute dtype, the [V, F] table never does (the
                # dequant-hot-path lint rule holds serve/ to this)
                params, qtab, qscale, ids, gctx = args
                x = (jnp.take(qtab, ids, axis=0).astype(self.compute)
                     * jnp.take(qscale, ids)[:, None]
                     .astype(self.compute))
            else:
                params, table, ids, gctx = args
                x = jnp.take(table, ids, axis=0)
            if self.flavor == "table":
                return x
            return self.head_model.apply(
                cast_floats(params, self.compute), x, gctx,
                key=None, train=False)
        params, feats, ids, gctx = args
        logits = self.model.apply(cast_floats(params, self.compute),
                                  feats, gctx, key=None, train=False)
        return jnp.take(logits, ids, axis=0)

    def _args_for(self, ids, pub: Optional[TableVersion] = None):
        """The per-dispatch argument tuple — ONE construction shared
        by the live call path and the candidate enumeration, so the
        auditor/prewarm keys and the runtime programs cannot drift.
        ``pub`` pins a captured table version (the microbatch server
        captures one per batch); None reads the current publication.
        Versions only swap the table VALUES, never its shape/dtype —
        within one qmode the program key is version-independent, and
        across qmodes the captured version routes to ITS mode's
        program (the quant-spec-pinned invariant)."""
        if pub is None:
            pub = self._published
        if pub.qmode != "off":
            return (self.params, pub.table, pub.scale, ids,
                    self._gctx)
        return (self.params, pub.table, ids, self._gctx)

    def serve_candidates(self) -> List[Any]:
        """The exact serve program set, as prewarmable auditor
        candidates (``analysis/programspace.Candidate``) — one program
        per bucket.  ``observed=False``: bucket sizes are request
        shapes, not partition shapes (the cache-key-drift rule's
        head-block exemption applies verbatim), but the programs still
        count against the ``program_budget`` ratchet and the prewarm
        driver AOT-compiles every one."""
        import jax
        import jax.numpy as jnp

        from ..analysis.programspace import Candidate
        cands: List[Any] = []
        quant = self.quant != "off"
        # the quantized 5-tuple splits the table role into codes +
        # scales (both version-swapped data planes); ids/gctx keep
        # their off-mode roles so the replication auditor sees the
        # same sharing story
        roles = (("params", "data", "data", "other", "tables")
                 if quant else ("params", "data", "other", "tables"))
        for b in self.buckets:
            ids = jax.ShapeDtypeStruct((b,), jnp.dtype(jnp.int32))
            args = self._args_for(ids)
            jit = self._jits[(self.quant, b)]._jit
            cands.append(Candidate(
                slot=self._slot(b, self.quant), fn=jit, args=args,
                donate=(), observed=False, roles=roles,
                aot=lambda j=jit, a=args: j.lower(*a).compile()))
        return cands

    def warm(self, cache_dir: Optional[str] = None,
             name: str = "serve") -> Dict[str, Any]:
        """AOT-compile every bucket program against the persistent
        cache (the export step calls this, and a cold server may too —
        first-query readiness becomes a warm-hit report instead of a
        latency spike)."""
        from ..utils.compile_cache import enable_compile_cache
        from ..utils.prewarm import warm_candidates
        d = enable_compile_cache(cache_dir, min_compile_secs=0.0)
        return warm_candidates(self.serve_candidates(), d, config=name,
                               verbose=self.verbose)

    def program_keys(self) -> List[str]:
        from ..obs.compile_watch import program_key_of
        return sorted(program_key_of(c.slot, c.args, c.donate)
                      for c in self.serve_candidates())

    # --------------------------------------------------------- queries

    def published(self) -> TableVersion:
        """A consistent snapshot of the current table version (one
        atomic attribute read).  Dispatch paths capture this ONCE per
        microbatch so every request in the batch is served from one
        version even while :meth:`invalidate` publishes a new one."""
        return self._published

    def query_device(self, ids_padded,
                     pub: Optional[TableVersion] = None):
        """One padded-bucket dispatch; returns the device logits
        ``[bucket, C]``.  ``ids_padded`` length must be a bucket."""
        b = int(ids_padded.shape[0])
        if pub is None:
            pub = self._published
        # the program is selected by the CAPTURED version's qmode —
        # a batch pinned to a fp32 version keeps running the fp32
        # program even after publish_quant lands int8 (quant-spec-
        # pinned, bit-exact per captured version)
        jit = self._jits.get((pub.qmode, b))
        if jit is None:
            raise ValueError(f"ids length {b} is not a bucket "
                             f"{self.buckets}")
        return jit(*self._args_for(ids_padded, pub))

    def query(self, node_ids,
              pub: Optional[TableVersion] = None) -> np.ndarray:
        """Synchronous convenience path: pad to the smallest fitting
        bucket, dispatch, fetch, slice.  The microbatch server
        (``serve/server.py``) is the production entry — it coalesces
        concurrent requests into one dispatch; this method is the
        single-caller form the parity tests pin."""
        import jax
        import jax.numpy as jnp
        ids = np.asarray(node_ids, dtype=np.int32).ravel()
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_nodes):
            raise ValueError(
                f"node ids out of range [0, {self.num_nodes})")
        if pub is None:
            pub = self.published()  # one version for every chunk
        out: List[np.ndarray] = []
        cap = max(self.buckets)
        for lo in range(0, ids.size, cap):
            chunk = ids[lo:lo + cap]
            b = bucket_for(chunk.size, self.buckets)
            padded = np.full(b, self.pad_id, dtype=np.int32)
            padded[:chunk.size] = chunk
            logits = self.query_device(jnp.asarray(padded), pub)
            # the result fetch IS this tier's product — the one
            # sanctioned host sync on the serve path
            got = jax.device_get(logits)  # roc-lint: ok=host-sync-hot-path
            out.append(np.asarray(got[:chunk.size], dtype=np.float32))
        return (np.concatenate(out) if out
                else np.zeros((0, self.num_classes or 0), np.float32))

    # ---------------------------------------------------- invalidation

    def invalidate(self, src, dst) -> int:
        """Edge-append invalidation hook: incrementally recompute the
        k-hop neighborhood rows of the propagation table
        (``PropagationCache.add_edges``) and publish a NEW table
        version carrying exactly those rows (``refresh_rows``).
        Returns the number of rows refreshed.  Control-plane op — the
        scatter below compiles a tiny program per affected-set shape,
        deliberately OUTSIDE the audited serve set (mutations are
        rare; quantizing them would complicate the hot path for
        nothing).  Mutators serialize on the publish lock; query
        threads never block on it (they read the published snapshot)."""
        if self.backend != "precomputed" or self.cache is None:
            raise NotImplementedError(
                "invalidation needs the precomputed backend (full-"
                "graph serving recomputes every dispatch anyway)")
        with self._pub_lock:
            rows = self.cache.add_edges(src, dst)
            version = self._publish_rows_locked(rows)
        self._emit_publish(version, rows)
        return int(rows.size)

    def refresh_rows(self, rows: np.ndarray) -> None:
        """Publish a new table version with ``rows`` re-uploaded from
        the host cache.  The previous version's device buffer is left
        untouched — in-flight dispatches pinned to it finish
        bit-exact (``.at[rows].set`` materializes a fresh buffer:
        copy-on-write at the device boundary)."""
        with self._pub_lock:
            version = self._publish_rows_locked(rows)
        self._emit_publish(version, rows)

    def _publish_rows_locked(self, rows: np.ndarray) -> Optional[int]:
        import jax.numpy as jnp
        if rows.size == 0:
            return None
        old = self._published
        idx = jnp.asarray(rows.astype(np.int32))
        if old.qmode != "off":
            # requantize ONLY the recomputed rows.  Per-row symmetric
            # scales are row-local, so these (q, scale) pairs are
            # bit-identical to quantizing a full rebuild of the
            # mutated table (tests/test_serve_quant.py pins it) —
            # incremental invalidation loses nothing to quantization.
            from .quant import QuantDriftError, quantize_rows
            q, sc = quantize_rows(self.cache.table[rows], old.qmode)
            guard = getattr(self, "_scale_guard", None)
            # host numpy scales (control-plane refresh, not a query)
            smax = float(sc.max())  # roc-lint: ok=host-sync-hot-path
            if guard is not None and smax > guard:
                # the post-invalidation drift re-check: a refreshed
                # row whose quantization step left the envelope the
                # export-time gate measured would serve coarser
                # values than anything validated — refuse BEFORE
                # publishing; the old version stays live and the
                # operator re-exports (re-gating) instead
                raise QuantDriftError(
                    f"invalidation refused: refreshed row scale "
                    f"{smax:.6g} exceeds the gated envelope "
                    f"{guard:.6g} (build max × slack); serving "
                    f"stays on v{old.version} — re-export to re-run "
                    f"the drift gate on the mutated graph")
            new_table = old.table.at[idx].set(jnp.asarray(q))
            new_scale = old.scale.at[idx].set(jnp.asarray(sc))
            self.table, self.scale = new_table, new_scale
            self._published = TableVersion(
                old.version + 1, new_table, new_scale, old.qmode)
            return old.version + 1
        vals = jnp.asarray(
            self.cache.table[rows].astype(np.float32),  # roc-lint: ok=dequant-hot-path
            dtype=self.compute)
        new_table = old.table.at[idx].set(vals)
        self.table = new_table
        self._published = TableVersion(
            old.version + 1, new_table, None, "off")
        return old.version + 1

    def publish_quant(self, mode: str) -> int:
        """Control-plane re-publication of the CURRENT host table
        under a new quant spec — the mid-rollout fp32→int8 (or back)
        swap.  The target mode's bucket programs are built before the
        publish so the hot path never constructs programs; the swap
        itself is one versioned publish, and in-flight batches pinned
        to the previous version finish on ITS mode's program against
        ITS buffers (quant-spec-pinned — the model checker's
        ``live-qmode`` seed shows what skipping the pin would serve).
        Returns the published version."""
        from .quant import check_mode
        if self.backend != "precomputed" or self.cache is None:
            raise NotImplementedError(
                "quant swaps apply to the precomputed table backend")
        mode = check_mode(mode)
        if (mode, self.buckets[0]) not in self._jits:
            self._build_jits(mode)
        with self._pub_lock:
            old = self._published
            table, scale = self._device_table(mode)
            self.table, self.scale = table, scale
            self.quant = mode
            version = old.version + 1
            self._published = TableVersion(version, table, scale,
                                           mode)
        emit("serve", f"table version {version} published "
             f"(quant swap {old.qmode}->{mode}; in-flight queries "
             f"finish on v{old.version}:{old.qmode})", console=False,
             kind="table_publish", version=version, rows=0,
             qmode=mode)
        return version

    def _emit_publish(self, version: Optional[int],
                      rows: np.ndarray) -> None:
        # after the publish lock is released: event I/O must never sit
        # on the mutation critical section (roc-lint
        # blocking-under-lock)
        if version is None:
            return
        emit("serve", f"table version {version} published "
             f"({rows.size} row(s) rewritten; in-flight queries "
             f"finish on v{version - 1})", console=False,
             kind="table_publish", version=version,
             rows=int(rows.size))
