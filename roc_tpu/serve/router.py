"""Multi-replica routing: N server replicas behind one ``submit``.

A :class:`Router` fronts N REAL replica subprocesses (each
``python -m roc_tpu.serve.replica`` cold-loading the same exported
artifact — see ``serve/replica.py`` for the wire protocol) behind the
same ``submit(node_ids) -> Future`` surface a single
:class:`~roc_tpu.serve.server.Server` offers, adding the availability
properties one process cannot have:

- **least-loaded dispatch** — each request goes to the eligible
  replica with the fewest in-flight requests.  Eligibility is
  *shard-aware*: a replica may advertise a ``[lo, hi)`` node range
  (the future 2-D mesh's table shards); requests spanning ranges are
  split per shard-group and reassembled in order — with today's
  full-range replicas that degenerates to pure least-loaded.
- **health + failover** — liveness rides the replica heartbeat lines
  (the ``obs`` heartbeat cadence, ``ROC_TPU_SERVE_HB_S``); a silent
  replica leaves a dated ``stall`` event exactly like a wedged bench
  stage.  When a replica dies (EOF/exit — the ``replica_sigkill``
  drill), its in-flight requests are requeued onto survivors and the
  failover lands as a timeline marker (``serve`` event,
  kind=``failover``).
- **hedged re-dispatch** — a request in flight longer than the
  ``hedge_pct`` percentile of completed latencies (floored at
  ``hedge_min_ms``) is duplicated onto a second replica; first answer
  wins.  This is what bounds the ``replica_stall`` drill: a stuck
  replica costs one hedge, not a hung client.
- **deadlines + backpressure** — the router's monitor expires pending
  requests past ``deadline_ms`` with typed ``ServeTimeout`` even when
  every replica is wedged (never a hang), and ``max_inflight`` sheds
  with ``ServeOverload`` at submit.

The failure contract is the serve tier's one contract
(``serve/errors.py``): an accepted request completes with a correct
answer or fails typed.  Replica-side *retryable* failures (the
``serve_io`` drill) are re-dispatched transparently, bounded by
``max_tries``; deadline/shed/closed failures propagate as themselves.

Observability (PR 17): every client submit mints a request id
(``rid``) that rides the wire to the replica and is stamped into the
replica server's microbatch spans — ``python -m roc_tpu.timeline
--request RID`` renders one request's full router → replica →
microbatch → table-version path, including splits, hedges, and
failover requeues (the hedge/failover markers carry the rid too).
All counting goes through a
:class:`~roc_tpu.obs.metrics_registry.MetricsRegistry` (roc-lint
``metric-adhoc``), so ``stats()`` reports *windowed* rates and p50/p99
alongside lifetime totals.  Pass ``slos=[...]`` (spec strings or
:class:`~roc_tpu.obs.slo.Slo`) to arm the burn-rate
:class:`~roc_tpu.obs.slo.SloEngine` over the router's registry: the
monitor loop ticks it, breaches emit dated ``slo`` events + a flight-
record dump, and :meth:`Router.health` returns the machine-readable
verdict.  ``snapshot_path`` (or ``ROC_TPU_SLO_SNAPSHOT``) makes the
monitor publish a 1 Hz registry+verdict snapshot JSON —
``watch -n1 python -m roc_tpu.report --slo <path>`` is the live
dashboard.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.events import emit
from ..obs.metrics_registry import MetricsRegistry
from ..obs.slo import SloEngine
from .errors import (ReplicaLost, ServeClosed, ServeError,
                     ServeOverload, ServeTimeout)
from .replica import hb_interval

# monitor cadence: deadline expiry + hedging both resolve on this
# grain, so it sits well under the smallest deadline worth setting
_MONITOR_TICK_S = 0.01

# typed names a replica may report; anything else maps to ServeError
_TYPED = {"ServeTimeout": ServeTimeout, "ServeOverload": ServeOverload,
          "ServeClosed": ServeClosed, "ValueError": ValueError}


class _Replica:
    """Router-side handle for one replica subprocess."""

    def __init__(self, idx: int, proc: subprocess.Popen):
        self.idx = idx
        self.proc = proc
        self.wlock = threading.Lock()
        self.alive = True
        self.requeued = False   # failover ran for this corpse already
        self.ready: Dict[str, Any] = {}
        self.shard: Optional[Tuple[int, int]] = None
        self.inflight = 0
        self.served = 0
        self.last_hb = time.monotonic()
        self.silent_noted = False
        self.reader: Optional[threading.Thread] = None

    def covers(self, lo: int, hi: int) -> bool:
        if self.shard is None:
            return True
        return self.shard[0] <= lo and hi <= self.shard[1]

    def send(self, obj: Dict[str, Any]) -> bool:
        line = json.dumps(obj) + "\n"
        try:
            with self.wlock:
                # per-replica pipe serializer; the hold is one small
                # flushed line (the event-bus JSONL precedent):
                # roc-lint: ok=blocking-under-lock
                self.proc.stdin.write(line)
                # same bounded hold: roc-lint: ok=blocking-under-lock
                self.proc.stdin.flush()
            return True
        except (OSError, ValueError):
            return False


class _Sub:
    """One wire request: a shard-slice of a client submit, assigned to
    (up to two, when hedged) replicas."""

    __slots__ = ("wire_id", "parent", "slot", "ids", "deadline_t",
                 "replica", "hedge_replica", "t_sent", "tries")

    def __init__(self, wire_id, parent, slot, ids, deadline_t):
        self.wire_id = wire_id
        self.parent = parent
        self.slot = slot
        self.ids = ids
        self.deadline_t = deadline_t
        self.replica: Optional[int] = None
        self.hedge_replica: Optional[int] = None
        self.t_sent = 0.0
        self.tries = 0


class _Parent:
    """One client submit: future + per-shard result slots, plus the
    minted request id and submit stamp the trace/latency metrics
    read."""

    __slots__ = ("fut", "n_left", "parts", "order", "version",
                 "rid", "t0")

    def __init__(self, fut: Future, n_slots: int, order,
                 rid: Optional[str] = None, t0: float = 0.0):
        self.fut = fut
        self.n_left = n_slots
        self.parts: List[Optional[np.ndarray]] = [None] * n_slots
        self.order = order
        self.version: Optional[int] = None
        self.rid = rid
        self.t0 = t0


class Router:
    """See module docstring.  ``Router(artifact_dir, n_replicas=2)``
    spawns the replicas; ``submit``/``query``/``stats``/``close``
    mirror :class:`~roc_tpu.serve.server.Server`."""

    def __init__(self, artifact_dir: str, n_replicas: int = 2,
                 shards: Optional[Sequence[Tuple[int, int]]] = None,
                 max_wait_ms: float = 0.2,
                 max_inflight: int = 1024,
                 default_deadline_ms: Optional[float] = None,
                 hedge_pct: float = 0.95,
                 hedge_min_ms: float = 50.0,
                 max_tries: int = 3,
                 cpu: bool = False,
                 ready_timeout_s: float = 180.0,
                 env: Optional[Dict[str, str]] = None,
                 replica_args: Optional[Sequence[str]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 stats_window_s: float = 60.0,
                 slos: Optional[Sequence[Any]] = None,
                 snapshot_path: Optional[str] = None,
                 sharded: bool = False,
                 table_budget_bytes: Optional[int] = None,
                 gather_rider_cap: int = 8):
        if n_replicas < 1:
            raise ValueError("need at least one replica")
        if shards is not None and len(shards) != n_replicas:
            raise ValueError("one shard range per replica")
        self._sharded = bool(sharded)
        self.table_budget_bytes = table_budget_bytes
        self.gather_rider_cap = int(gather_rider_cap)
        # in-flight cross-shard gathers: gid -> (requester replica idx,
        # owner replica idx); an owner dying mid-gather answers its
        # outstanding gids with the error variant of ``rows`` so the
        # requester's pinned gather fails typed instead of timing out
        self._gathers: Dict[str, Tuple[int, int]] = {}
        if sharded:
            # derive one replica per exported table slice: each spawns
            # with --shard-index K and cold-loads O(V/N)+halo bytes
            from .export import MANIFEST_NAME
            with open(os.path.join(artifact_dir, MANIFEST_NAME)) as f:
                sb = json.load(f).get("shards") or {}
            if not sb:
                raise ValueError(
                    f"{artifact_dir}: sharded=True but the artifact "
                    f"was not exported with --shards")
            if shards is not None:
                raise ValueError("sharded=True derives the shard "
                                 "ranges from the artifact; drop "
                                 "shards=")
            if n_replicas != int(sb["n"]):
                raise ValueError(
                    f"sharded artifact has {sb['n']} slice(s); "
                    f"n_replicas={n_replicas} must match")
            shards = [(int(lo), int(hi)) for lo, hi in sb["plan"]]
        self.artifact_dir = artifact_dir
        self.max_inflight = int(max_inflight)
        self.default_deadline_ms = default_deadline_ms
        self.hedge_pct = float(hedge_pct)
        self.hedge_min_ms = float(hedge_min_ms)
        self.max_tries = int(max_tries)
        self.stats_window_s = float(stats_window_s)
        self._lock = threading.Lock()
        self._pending: Dict[int, _Sub] = {}
        self._next_id = 0
        self._rid_seq = 0
        self._closed = False
        self._stop = threading.Event()
        # ALL counting goes through the registry: lifetime totals AND
        # windowed rates from one recording (roc-lint metric-adhoc)
        self.reg = (registry if registry is not None
                    else MetricsRegistry("router"))
        self._c_requests = self.reg.counter("requests")
        self._c_shed = self.reg.counter("shed")
        self._c_timeout = self.reg.counter("timeout")
        self._c_failover = self.reg.counter("failover")
        self._c_hedge = self.reg.counter("hedge")
        self._c_ok = self.reg.counter("ok")
        self._c_failed = self.reg.counter("failed")
        # wire_ms: per-sub replica round trips (the hedge threshold's
        # base); request_ms: client submit -> assembled result (the
        # p99 the latency SLO guards)
        self._h_wire = self.reg.histogram("wire_ms")
        self._h_request = self.reg.histogram("request_ms")
        # per-microbatch cross-shard gather wall, from res.gather_ms —
        # the request-path cost of serving O(V/N) tables
        self._h_gather = self.reg.histogram("gather_ms")
        self._spans: List[Tuple[str, float, float,
                                Dict[str, Any]]] = []
        self._slo: Optional[SloEngine] = None
        if slos:
            self._slo = SloEngine(self.reg, slos, component="router")
        self.snapshot_path = (snapshot_path
                              or os.environ.get("ROC_TPU_SLO_SNAPSHOT")
                              or None)
        self._last_snapshot = 0.0
        self.num_nodes: Optional[int] = None
        # the router's own lane handshake, like Server's
        emit("timeline", f"clock_sync: serve router up "
             f"({n_replicas} replica(s) over {artifact_dir})",
             console=False, kind="clock_sync", server="router")
        self._replica_args = list(replica_args or [])
        self._monitor: Optional[threading.Thread] = None
        self.replicas: List[_Replica] = []
        for i in range(n_replicas):
            self.replicas.append(self._spawn(
                i, shards[i] if shards else None, max_wait_ms, cpu,
                env))
        self._await_ready(ready_timeout_s)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="router:monitor",
            daemon=True)
        self._monitor.start()

    # ------------------------------------------------------- lifecycle

    def _spawn(self, idx: int, shard, max_wait_ms: float, cpu: bool,
               env: Optional[Dict[str, str]]) -> _Replica:
        cmd = [sys.executable, "-m", "roc_tpu.serve.replica",
               self.artifact_dir, "--replica", str(idx),
               "--max-wait-ms", str(max_wait_ms)]
        if self._sharded:
            # the real sliced-table load; the replica derives its
            # owned [lo, hi) range (and the gather plan) from the
            # artifact's shard manifest
            cmd += ["--shard-index", str(idx)]
        elif shard is not None:
            cmd += ["--shard", f"{shard[0]}:{shard[1]}"]
        if self.table_budget_bytes:
            cmd += ["--table-budget-bytes",
                    str(self.table_budget_bytes)]
        if cpu:
            cmd += ["--cpu"]
        cmd += self._replica_args
        child_env = dict(env) if env is not None else os.environ.copy()
        # `-m roc_tpu.serve.replica` must resolve regardless of the
        # caller's cwd (a bench child runs from an arbitrary dir):
        # the package's parent dir rides PYTHONPATH
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        child_env["PYTHONPATH"] = (
            pkg_root + os.pathsep + child_env["PYTHONPATH"]
            if child_env.get("PYTHONPATH") else pkg_root)
        proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=child_env)
        rep = _Replica(idx, proc)
        if shard is not None:
            rep.shard = (int(shard[0]), int(shard[1]))
        rep.reader = threading.Thread(
            target=self._read_loop, args=(rep,),
            name=f"router:read{idx}", daemon=True)
        rep.reader.start()
        return rep

    def _await_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                ready = [r for r in self.replicas if r.ready]
                dead = [r for r in self.replicas if not r.alive]
            if dead:
                self.close()
                raise ServeError(
                    f"replica(s) {[r.idx for r in dead]} died during "
                    f"startup (see stderr)")
            if len(ready) == len(self.replicas):
                self.num_nodes = int(ready[0].ready["num_nodes"])
                emit("serve", f"router ready: {len(ready)} replica(s), "
                     f"V={self.num_nodes}", console=False,
                     kind="router_ready", replicas=len(ready))
                return
            time.sleep(0.05)
        self.close()
        raise ServeError(f"replicas not ready within {timeout_s:.0f}s")

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        self._stop.set()
        for sub in pending:
            if not sub.parent.fut.done():
                sub.parent.fut.set_exception(
                    ServeClosed("router closed with requests in "
                                "flight"))
        # graceful first: close stdin → replica drains and exits 0
        for rep in self.replicas:
            try:
                rep.proc.stdin.close()
            except (OSError, ValueError):
                pass
        deadline = time.monotonic() + 15.0
        for rep in self.replicas:
            try:
                rep.proc.wait(timeout=max(0.1,
                                          deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                # a wedged replica (the replica_stall drill) cannot
                # drain — escalate the way bench does: TERM, then KILL
                rep.proc.terminate()
                try:
                    rep.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
                    rep.proc.wait()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        for rep in self.replicas:
            if rep.reader is not None:
                rep.reader.join(timeout=5.0)
        self._flush_spans(final=True)
        s = self.stats()
        emit("serve", f"router closed: {s['n_ok']} ok / "
             f"{s['n_timeout']} timeout / {s['n_shed']} shed / "
             f"{s['n_failover']} failover / {s['n_hedge']} hedge",
             console=False, kind="router_summary", **s)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- submit

    def submit(self, node_ids,
               deadline_ms: Optional[float] = None) -> Future:
        """One client request; resolves to the fp32 ``[n, C]`` logits
        or a typed ``serve/errors.py`` failure.  Mints the request id
        (``rid``) the distributed trace connects on."""
        ids = np.asarray(node_ids, dtype=np.int32).ravel()
        fut: Future = Future()
        if ids.size and self.num_nodes is not None and (
                ids.min() < 0 or ids.max() >= self.num_nodes):
            fut.set_exception(ValueError(
                f"node ids out of range [0, {self.num_nodes})"))
            return fut
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        t0 = time.monotonic()
        deadline_t = (None if deadline_ms is None
                      else t0 + max(0.0, deadline_ms) / 1e3)
        groups = self._shard_groups(ids)
        with self._lock:
            if self._closed:
                fut.set_exception(ServeClosed("router is closed"))
                return fut
            self._c_requests.inc()
            if len(self._pending) + len(groups) > self.max_inflight:
                self._c_shed.inc()
                fut.set_exception(ServeOverload(
                    f"router in-flight cap {self.max_inflight} "
                    f"reached — load shed"))
                return fut
            self._rid_seq += 1
            rid = f"{os.getpid():x}-{self._rid_seq}"
            parent = _Parent(fut, len(groups),
                             [g[1] for g in groups], rid=rid, t0=t0)
            subs = []
            for slot, (gids, _order) in enumerate(groups):
                wire_id = self._next_id
                self._next_id += 1
                sub = _Sub(wire_id, parent, slot, gids, deadline_t)
                self._pending[wire_id] = sub
                subs.append(sub)
        for sub in subs:
            self._dispatch(sub)
        return fut

    def query(self, node_ids,
              deadline_ms: Optional[float] = None) -> np.ndarray:
        return self.submit(node_ids, deadline_ms=deadline_ms).result()

    def _shard_groups(self, ids: np.ndarray):
        """Split ``ids`` into per-shard-group sub-requests.  Returns
        ``[(gids, positions)]``; with full-range replicas this is one
        group carrying everything.

        Sharded fleets (PR 20): requests at or under
        ``gather_rider_cap`` ids stay ONE wire sub — the majority
        owner serves them, fetching the foreign rows through its
        cross-shard gather leg (splitting a tiny request across N
        replicas would trade one gather for N wire round trips).
        Larger requests split per owner range as before; ids outside
        every advertised range no longer require a full-range
        fallback replica — ANY replica serves them via gather."""
        ranges = sorted({r.shard for r in self.replicas
                         if r.shard is not None})
        if not ranges:
            return [(ids, np.arange(ids.size))]
        if ids.size <= self.gather_rider_cap:
            return [(ids, np.arange(ids.size))]
        groups = []
        claimed = np.zeros(ids.size, dtype=bool)
        for lo, hi in ranges:
            mask = (ids >= lo) & (ids < hi) & ~claimed
            if mask.any():
                claimed |= mask
                groups.append((ids[mask], np.nonzero(mask)[0]))
        if not claimed.all():
            # ids outside every advertised range ride one extra group;
            # _pick_replica lands it on the least-loaded live replica
            # and the gather leg makes that correct (the old "any
            # full-range replica" fallback is gone)
            rest = ~claimed
            groups.append((ids[rest], np.nonzero(rest)[0]))
        return groups or [(ids, np.arange(ids.size))]

    # -------------------------------------------------------- dispatch

    def _pick_replica(self, sub: _Sub,
                      exclude: Sequence[int] = ()) -> Optional[_Replica]:
        lo = int(sub.ids.min()) if sub.ids.size else 0
        hi = int(sub.ids.max()) + 1 if sub.ids.size else 0
        with self._lock:
            # exclude is HARD: a hedge must never land back on the
            # replica it hedges around (a wedged-but-alive replica
            # would absorb its own hedge and defeat the bound), and a
            # broken-pipe exclude must never be re-picked mid-loop
            cands = [r for r in self.replicas
                     if r.alive and r.ready and r.idx not in exclude]
            if not cands:
                return None
            covering = [r for r in cands if r.covers(lo, hi)]
            if covering:
                return min(covering, key=lambda r: r.inflight)
            # no single replica owns the whole sub (a gather-rider
            # request, or uncovered ids after an owner died): route to
            # the MAJORITY owner, least-loaded on ties — the foreign
            # minority arrives through its gather leg
            def owned(r: _Replica) -> int:
                if r.shard is None:
                    return int(sub.ids.size)
                return int(((sub.ids >= r.shard[0])
                            & (sub.ids < r.shard[1])).sum())
            return max(cands, key=lambda r: (owned(r), -r.inflight))

    def _dispatch(self, sub: _Sub, hedge: bool = False) -> None:
        """Assign ``sub`` to the least-loaded eligible replica and put
        it on the wire; a dead pipe fails over immediately."""
        exclude = ([sub.replica] if hedge and sub.replica is not None
                   else [])
        while True:
            rep = self._pick_replica(sub, exclude=exclude)
            if rep is None:
                if hedge:
                    return     # nowhere to hedge — original still owns
                self._fail_sub(sub, ReplicaLost(
                    "no live replica covers this request's shard"))
                return
            remaining_ms = (None if sub.deadline_t is None else
                            max(0.0, (sub.deadline_t - time.monotonic())
                                * 1e3))
            ok = rep.send({"kind": "req", "id": sub.wire_id,
                           "ids": sub.ids.tolist(),
                           "deadline_ms": remaining_ms,
                           "rid": sub.parent.rid})
            if ok:
                with self._lock:
                    rep.inflight += 1
                    if hedge:
                        sub.hedge_replica = rep.idx
                    else:
                        sub.replica = rep.idx
                        sub.t_sent = time.monotonic()
                        sub.tries += 1
                return
            # broken pipe: this replica is gone.  Requeue its OTHER
            # in-flight requests (skip= keeps THIS sub out — the loop
            # below re-dispatches it itself, a double-send would act
            # like an accidental hedge)
            self._mark_dead(rep, "write failed", skip=sub)
            exclude = list(exclude) + [rep.idx]

    def _fail_sub(self, sub: _Sub, exc: Exception) -> None:
        """Fail the whole parent (pop every sibling sub).  Counts ONE
        failure per parent, and only when the request was actually
        still pending — a request completed by _on_result in the
        monitor's snapshot-to-call window, or a sibling of an
        already-failed parent, must not inflate the stats."""
        with self._lock:
            popped = self._pending.pop(sub.wire_id, None) is not None
            for wid, other in list(self._pending.items()):
                if other.parent is sub.parent:
                    self._pending.pop(wid)
                    popped = True
            count = popped and not sub.parent.fut.done()
        if count:
            if isinstance(exc, ServeTimeout):
                self._c_timeout.inc()
            self._c_failed.inc()
        if count and not sub.parent.fut.done():
            try:
                sub.parent.fut.set_exception(exc)
            except Exception:  # noqa: BLE001 - lost the completion race
                pass

    # --------------------------------------------------------- readers

    def _read_loop(self, rep: _Replica) -> None:
        try:
            for line in rep.proc.stdout:
                if self._stop.is_set():
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                kind = msg.get("kind")
                if kind == "ready":
                    with self._lock:
                        rep.ready = msg
                        if msg.get("shard"):
                            rep.shard = tuple(msg["shard"])
                        rep.last_hb = time.monotonic()
                elif kind == "hb":
                    with self._lock:
                        rep.last_hb = time.monotonic()
                        rep.silent_noted = False
                elif kind == "res":
                    self._on_result(rep, msg)
                elif kind == "fetch_rows":
                    self._forward_fetch(rep, msg)
                elif kind == "rows":
                    self._relay_rows(rep, msg)
                elif kind == "drained":
                    with self._lock:
                        rep.last_hb = time.monotonic()
                else:
                    # explicit unknown-kind rejection: a replica
                    # speaking a newer/typo'd protocol fails loud
                    # on the bus instead of being silently ignored
                    emit("serve",
                         f"replica {rep.idx} sent unknown wire "
                         f"kind {kind!r} — dropped", console=False,
                         kind_rejected=str(kind), replica=rep.idx)
        except (OSError, ValueError):
            pass
        finally:
            self._mark_dead(rep, "stdout EOF")

    def _forward_fetch(self, rep: _Replica,
                       msg: Dict[str, Any]) -> None:
        """Gather leg, requester → owner: forward a version-pinned row
        fetch to the live replica OWNING the ids' range (the line is
        re-built, not relayed raw — the declared field contract is the
        send site's shape on both channels).  No live owner → the
        requester gets the error variant of ``rows`` immediately."""
        gid = str(msg.get("gid"))
        ids = [int(i) for i in (msg.get("ids") or [])]
        version = int(msg.get("version") or 0)
        lo = min(ids) if ids else 0
        hi = (max(ids) + 1) if ids else 0
        owner: Optional[_Replica] = None
        with self._lock:
            for r in self.replicas:
                if (r.alive and r.ready and r.idx != rep.idx
                        and r.shard is not None and r.covers(lo, hi)):
                    owner = r
                    break
            if owner is not None:
                self._gathers[gid] = (rep.idx, owner.idx)
        if owner is not None:
            ok = owner.send({"kind": "fetch_rows", "gid": gid,
                             "ids": ids, "version": version})
            if ok:
                return
            with self._lock:
                self._gathers.pop(gid, None)
            self._mark_dead(owner, "write failed")
        rep.send({"kind": "rows", "gid": gid, "ids": ids, "rows": [],
                  "version": version, "qmode": "off", "scales": None,
                  "replica": None,
                  "error": "ReplicaLost: no live replica owns these "
                           "rows"})

    def _relay_rows(self, rep: _Replica, msg: Dict[str, Any]) -> None:
        """Gather leg, owner → requester: relay the owner's answer
        back to the replica whose gid this is (re-built line, same
        contract note as :meth:`_forward_fetch`)."""
        gid = str(msg.get("gid"))
        requester: Optional[_Replica] = None
        with self._lock:
            entry = self._gathers.pop(gid, None)
            if entry is not None:
                for r in self.replicas:
                    if r.idx == entry[0]:
                        requester = r
                        break
        if requester is None or not requester.alive:
            return      # requester died mid-gather; nothing to do
        requester.send({"kind": "rows", "gid": gid,
                        "ids": msg.get("ids"),
                        "rows": msg.get("rows"),
                        "version": msg.get("version"),
                        "qmode": msg.get("qmode"),
                        "scales": msg.get("scales"),
                        "replica": rep.idx,
                        "error": msg.get("error")})

    def _on_result(self, rep: _Replica, msg: Dict[str, Any]) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            sub = self._pending.get(msg.get("id"))
            if sub is not None and msg.get("ok"):
                del self._pending[sub.wire_id]
                rep.served += 1
                wire_ms = (time.monotonic() - sub.t_sent) * 1e3
        if sub is not None and msg.get("ok"):
            self._h_wire.record(wire_ms)
            gms = msg.get("gather_ms")
            if gms is not None:
                self._h_gather.record(float(gms))
        if sub is None:
            return   # hedge already won (or expired): late twin
        if msg.get("ok"):
            rows = np.asarray(msg["rows"], dtype=np.float32)
            self._complete(sub, rows, msg.get("version"))
            return
        # typed failure from the replica
        retryable = bool(msg.get("retryable"))
        if retryable:
            with self._lock:
                still = sub.wire_id in self._pending
                tries = sub.tries
            if still and tries < self.max_tries:
                emit("serve", f"retryable failure on replica "
                     f"{rep.idx} ({msg.get('error')}) — "
                     f"re-dispatching", console=False,
                     kind="redispatch", replica=rep.idx,
                     error=msg.get("error"))
                self._dispatch(sub)
                return
        exc_type = _TYPED.get(msg.get("error"), ServeError)
        self._fail_sub(sub, exc_type(
            f"replica {rep.idx}: {msg.get('msg', msg.get('error'))}"))

    def _complete(self, sub: _Sub, rows: np.ndarray,
                  version: Optional[int]) -> None:
        parent = sub.parent
        done = False
        with self._lock:
            parent.parts[sub.slot] = rows
            if version is not None:
                parent.version = (version if parent.version is None
                                  else max(parent.version, version))
            parent.n_left -= 1
            done = parent.n_left == 0
        if not done:
            return
        self._c_ok.inc()
        ms = (time.monotonic() - parent.t0) * 1e3
        self._h_request.record(ms)
        # the router-lane span for this request's trace (flushed in
        # batches like Server's)
        with self._lock:
            self._spans.append(
                ("route_request", parent.t0, ms,
                 {"rid": parent.rid,
                  "version": int(parent.version or 0)}))
            flush = len(self._spans) >= 64
        if flush:
            self._flush_spans()
        if parent.fut.done():
            return
        if len(parent.parts) == 1:
            out = parent.parts[0]
        else:
            n = sum(p.shape[0] for p in parent.parts)
            out = np.empty((n, parent.parts[0].shape[1]), np.float32)
            for part, pos in zip(parent.parts, parent.order):
                out[np.asarray(pos)] = part
        from .server import ServeResult
        res = out.view(ServeResult)
        res.version = int(parent.version or 0)
        parent.fut.set_result(res)

    # -------------------------------------------------- failover/hedge

    def _mark_dead(self, rep: _Replica, why: str,
                   skip: Optional[_Sub] = None) -> None:
        """Mark a replica dead and fail over its in-flight requests —
        exactly once per corpse, whichever of the reader (EOF), the
        monitor (poll), or a failed write gets here first."""
        with self._lock:
            was_alive = rep.alive
            rep.alive = False
            if rep.requeued or self._closed:
                if not was_alive:
                    return
                orphans = []
            else:
                rep.requeued = True
                orphans = [s for s in self._pending.values()
                           if (s.replica == rep.idx
                               or s.hedge_replica == rep.idx)
                           and s is not skip]
            closed = self._closed
            # gathers where the corpse was the OWNER get an error
            # answer (the requester retries → GatherError → retryable
            # res → re-dispatch); requester-side entries just drop.
            owed = [(gid, req_idx) for gid, (req_idx, own_idx)
                    in self._gathers.items()
                    if own_idx == rep.idx or req_idx == rep.idx]
            notify = []
            for gid, req_idx in owed:
                del self._gathers[gid]
                if req_idx == rep.idx:
                    continue
                for r in self.replicas:
                    if r.idx == req_idx and r.alive:
                        notify.append((gid, r))
                        break
        for gid, requester in notify:
            requester.send({"kind": "rows", "gid": gid, "ids": [],
                            "rows": [], "version": -1, "qmode": "off",
                            "scales": None, "replica": rep.idx,
                            "error": "ReplicaLost: owner died "
                                     "mid-gather"})
        if closed or (not was_alive and not orphans):
            return
        # the failover marker the timeline renders on the router lane;
        # rids connect it into each requeued request's trace
        rids = sorted({s.parent.rid for s in orphans
                       if s.parent.rid is not None})
        self._c_failover.inc(len(orphans))
        emit("serve", f"replica {rep.idx} died ({why}): failing over "
             f"{len(orphans)} in-flight request(s)",
             kind="failover", replica=rep.idx, requeued=len(orphans),
             rids=rids)
        for sub in orphans:
            if sub.hedge_replica == rep.idx:
                with self._lock:
                    sub.hedge_replica = None
                continue
            # requeue onto a survivor (deadline still enforced by the
            # monitor; a request whose deadline already passed expires
            # there as ServeTimeout, never silently dropped)
            self._dispatch(sub)

    def _hedge_threshold_ms(self) -> float:
        # windowed first (current behavior under load shifts), whole-
        # ring fallback; the log-bucket quantile's ~16% grain is fine
        # for a 2x-padded hedge trigger
        q = (self._h_wire.quantile(self.hedge_pct,
                                   self.stats_window_s)
             or self._h_wire.quantile(self.hedge_pct, None))
        if q is None:
            return self.hedge_min_ms
        return max(self.hedge_min_ms, q * 2.0)

    def _monitor_loop(self) -> None:
        hb_timeout = 3.0 * hb_interval()
        while not self._stop.wait(_MONITOR_TICK_S):
            now = time.monotonic()
            # deadline expiry — authoritative, replica-independent:
            # this is the "never a hang" backstop
            with self._lock:
                expired = [s for s in self._pending.values()
                           if s.deadline_t is not None
                           and s.deadline_t <= now]
            for sub in expired:
                self._fail_sub(sub, ServeTimeout(
                    "deadline expired in flight"))
            # hedging: slow in-flight subs get a second replica
            thr_s = self._hedge_threshold_ms() / 1e3
            with self._lock:
                slow = [s for s in self._pending.values()
                        if s.hedge_replica is None and s.t_sent
                        and now - s.t_sent > thr_s
                        and len([r for r in self.replicas
                                 if r.alive]) > 1]
            for sub in slow:
                self._c_hedge.inc()
                emit("serve", f"hedging request {sub.wire_id} "
                     f"(in flight {1e3 * (now - sub.t_sent):.0f} ms "
                     f"on replica {sub.replica})", console=False,
                     kind="hedge", replica=sub.replica,
                     rid=sub.parent.rid)
                self._dispatch(sub, hedge=True)
            # health: dead processes + silent heartbeats
            for rep in list(self.replicas):
                if rep.alive and rep.proc.poll() is not None:
                    self._mark_dead(rep,
                                    f"exit rc={rep.proc.returncode}")
                    continue
                with self._lock:
                    silent = (rep.alive and rep.ready
                              and now - rep.last_hb > hb_timeout
                              and not rep.silent_noted)
                    if silent:
                        rep.silent_noted = True
                        age = now - rep.last_hb
                if silent:
                    # same evidence trail as a wedged bench stage
                    emit("stall", f"replica {rep.idx} heartbeat "
                         f"silent for {age:.1f}s",
                         stage=f"serve_replica{rep.idx}",
                         elapsed_s=round(age, 1))
            # SLO evaluation (rate-limited inside tick) + the live
            # dashboard feed
            if self._slo is not None:
                self._slo.tick()
            if (self.snapshot_path
                    and now - self._last_snapshot >= 1.0):
                self._last_snapshot = now
                extra = {"component": "router",
                         "health": (self._slo.tick()
                                    if self._slo is not None
                                    else None)}
                self.reg.dump(self.snapshot_path,
                              windows=(10.0, self.stats_window_s),
                              extra=extra)

    def _flush_spans(self, final: bool = False) -> None:
        with self._lock:
            spans, self._spans = self._spans, []
        if not spans:
            return
        emit("timeline",
             f"spans: {len(spans)} routed request(s)"
             + (" (final)" if final else ""), console=False,
             kind="spans",
             spans=[[n, round(t0, 6), round(ms, 3), args]
                    for n, t0, ms, args in spans])

    # ----------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        """Lifetime ``n_*`` totals + *windowed* rates and latency
        quantiles over the trailing ``window_s`` seconds (``None``
        when the window saw no requests)."""
        w = self.stats_window_s
        with self._lock:
            reps = [{"replica": r.idx, "alive": r.alive,
                     "inflight": r.inflight, "served": r.served,
                     "shard": list(r.shard) if r.shard else None}
                    for r in self.replicas]
        n_req = self._c_requests.total
        n_shed = self._c_shed.total
        out = {"n_submitted": n_req - n_shed, "n_ok": self._c_ok.total,
               "n_failed": self._c_failed.total,
               "n_timeout": self._c_timeout.total,
               "n_shed": n_shed,
               "n_failover": self._c_failover.total,
               "n_hedge": self._c_hedge.total,
               "replicas": reps,
               "window_s": w}
        w_denom = self._c_requests.sum_over(w)

        def rate(num: int) -> Optional[float]:
            return round(num / w_denom, 4) if w_denom > 0 else None

        def q(h, p: float) -> Optional[float]:
            v = h.quantile(p, None)
            return round(v, 4) if v is not None else None

        out["p50_ms"] = q(self._h_request, 0.50)
        out["p99_ms"] = q(self._h_request, 0.99)
        out["gather_p50_ms"] = q(self._h_gather, 0.50)
        out["shed_rate"] = rate(self._c_shed.sum_over(w))
        out["error_rate"] = rate(self._c_failed.sum_over(w))
        out["availability"] = rate(self._c_ok.sum_over(w))
        return out

    def health(self) -> Dict[str, Any]:
        """Machine-readable serving health: the SLO engine's verdict
        (fresh evaluation) + replica liveness.  ``ok`` is the one bit
        an autoscaler/pager keys on: every objective in-state AND at
        least one replica alive."""
        alive = sum(1 for r in self.replicas if r.alive)
        if self._slo is None:
            v: Dict[str, Any] = {"ok": True, "states": {},
                                 "objectives": []}
        else:
            v = self._slo.verdict()
        v = dict(v)
        v["replicas_alive"] = alive
        v["replicas"] = len(self.replicas)
        v["ok"] = bool(v["ok"]) and alive > 0
        return v
