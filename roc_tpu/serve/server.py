"""Microbatch request queue: many concurrent queries, one device
dispatch.

``Server.submit(node_ids) -> Future`` is the serving tier's public
face: a dispatcher thread drains whatever requests are queued, packs
them into ONE padded, bucket-quantized device dispatch
(``Predictor.query_device``), and completes each caller's future with
its slice of the result.  Coalescing is bit-exact: every served row is
an independent dot-product chain, so a row's logits are identical
whether it shipped alone or inside a 512-wide microbatch
(tests/test_serve.py pins this).

Observability: the server emits a ``clock_sync`` timeline handshake at
startup (so the merged Perfetto trace gives the server process its own
aligned lane) and batches a ``serve_batch`` span per microbatch into
the same ``timeline``-category span events the trainers use — the
request pipeline renders next to the training lanes with zero new
merger code.  A ``serve`` summary event (queries, batches, latency
percentiles) closes the session.

The request loop is a hot path under roc-lint's
``host-sync-hot-path`` rule (``analysis/ast_lint.py`` scopes
``roc_tpu/serve/`` in): the ONLY device→host sync is the result fetch
inside the predictor, which is the product.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.events import emit
from .predictor import Predictor, bucket_for

# spans accumulate and flush as ONE timeline event per this many
# microbatches (and at close) — per-batch emits would put JSONL I/O on
# the request path
_SPAN_FLUSH_EVERY = 64


class Server:
    """Coalescing dispatcher over a :class:`Predictor`.

    ``max_wait_ms`` bounds how long the dispatcher lingers after the
    first queued request to let concurrent submitters join the batch
    (0 = dispatch immediately; the default 0.2 ms trades ~a fifth of a
    millisecond of p50 for a much fatter microbatch under load).
    """

    def __init__(self, predictor: Predictor,
                 max_wait_ms: float = 0.2,
                 name: str = "serve"):
        self.pred = predictor
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.name = name
        self._lock = threading.Condition()
        self._queue: List[Tuple[np.ndarray, Future]] = []
        self._closed = False
        self._spans: List[Tuple[str, float, float]] = []
        self._batch_ms: List[float] = []
        self._batch_n: List[int] = []
        self._n_queries = 0
        # the lane handshake: wall/mono stamped by the bus — the
        # timeline merger aligns this process's spans on it
        emit("timeline", f"clock_sync: serve server '{name}' up "
             f"(backend={predictor.backend})", console=False,
             kind="clock_sync", server=name)
        self._thread = threading.Thread(target=self._loop,
                                        name=f"serve:{name}",
                                        daemon=True)
        self._thread.start()

    # ---------------------------------------------------------- public

    def submit(self, node_ids) -> Future:
        """Queue a query; the returned future resolves to the fp32
        ``[len(node_ids), C]`` logits."""
        ids = np.asarray(node_ids, dtype=np.int32).ravel()
        fut: Future = Future()
        if ids.size and (ids.min() < 0
                         or ids.max() >= self.pred.num_nodes):
            fut.set_exception(ValueError(
                f"node ids out of range [0, {self.pred.num_nodes})"))
            return fut
        with self._lock:
            if self._closed:
                fut.set_exception(RuntimeError("server is closed"))
                return fut
            self._queue.append((ids, fut))
            self._n_queries += 1
            self._lock.notify()
        return fut

    def query(self, node_ids) -> np.ndarray:
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(node_ids).result()

    def stats(self) -> Dict[str, Any]:
        """Microbatch accounting since startup.  Snapshots under the
        server lock: the dispatcher thread appends to these series
        concurrently (roc-lint unguarded-shared-state — a sorted()
        over a list mid-append is exactly the race class)."""
        with self._lock:
            ms = sorted(self._batch_ms)
            batch_n = list(self._batch_n)
            n_queries = self._n_queries

        def pct(p: float) -> Optional[float]:
            if not ms:
                return None
            q = ms[min(len(ms) - 1, int(p * len(ms)))]
            return round(q, 4)

        mean_rows = np.mean(batch_n) if batch_n else None
        return {"n_queries": n_queries,
                "n_batches": len(ms),
                "rows_per_batch": (round(float(mean_rows), 2)
                                   if mean_rows is not None else None),
                "batch_p50_ms": pct(0.50),
                "batch_p99_ms": pct(0.99)}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._lock.notify()
        self._thread.join(timeout=10.0)
        self._flush_spans(final=True)
        s = self.stats()
        emit("serve", f"server '{self.name}' closed: "
             f"{s['n_queries']} queries in {s['n_batches']} batches "
             f"(p50 {s['batch_p50_ms']} ms)", console=False,
             kind="summary", **s)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- dispatcher

    def _take_batch(self) -> Optional[List[Tuple[np.ndarray, Future]]]:
        """Block for work; after the first request, linger up to
        ``max_wait_s`` so concurrent submitters coalesce.  Returns
        None at shutdown."""
        with self._lock:
            while not self._queue and not self._closed:
                self._lock.wait()
            if not self._queue:
                return None
        if self.max_wait_s > 0:
            deadline = time.monotonic() + self.max_wait_s
            cap = max(self.pred.buckets)
            while time.monotonic() < deadline:
                with self._lock:
                    if (sum(i.size for i, _ in self._queue) >= cap
                            or self._closed):
                        break
                time.sleep(self.max_wait_s / 8.0)
        with self._lock:
            batch, self._queue = self._queue, []
        return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 - fail the futures
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)

    def _dispatch(self, batch: List[Tuple[np.ndarray, Future]]) -> None:
        ids = (np.concatenate([i for i, _ in batch])
               if len(batch) > 1 else batch[0][0])
        t0 = time.monotonic()
        rows = self.pred.query(ids)
        ms = (time.monotonic() - t0) * 1e3
        # the device dispatch above runs UNLOCKED; only the bounded
        # bookkeeping appends hold the lock (stats() reads them from
        # caller threads), and the span flush emits after release —
        # an emit under the lock would put JSONL I/O on submit()'s
        # wait path (roc-lint blocking-under-lock)
        with self._lock:
            self._batch_ms.append(ms)
            self._batch_n.append(int(ids.size))
            self._spans.append(("serve_batch", t0, ms))
            flush = len(self._spans) >= _SPAN_FLUSH_EVERY
        if flush:
            self._flush_spans()
        lo = 0
        for req_ids, fut in batch:
            fut.set_result(rows[lo:lo + req_ids.size])
            lo += req_ids.size

    def _flush_spans(self, final: bool = False) -> None:
        with self._lock:
            spans, self._spans = self._spans, []
        if not spans:
            return
        emit("timeline",
             f"spans: {len(spans)} microbatch(es)"
             + (" (final)" if final else ""), console=False,
             kind="spans", spans=[[n, round(t0, 6), round(ms, 3)]
                                  for n, t0, ms in spans])
