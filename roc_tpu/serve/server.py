"""Microbatch request queue: many concurrent queries, one device
dispatch — with deadlines, backpressure, and versioned-table pinning.

``Server.submit(node_ids, deadline_ms=...) -> Future`` is the serving
tier's public face: a dispatcher thread drains whatever requests are
queued, packs them into ONE padded, bucket-quantized device dispatch
(``Predictor.query_device``), and completes each caller's future with
its slice of the result.  Coalescing is bit-exact: every served row is
an independent dot-product chain, so a row's logits are identical
whether it shipped alone or inside a 512-wide microbatch
(tests/test_serve.py pins this).

The robustness contract (ISSUE 13, drilled in
tests/test_serve_robustness.py) — an accepted request either completes
with a correct answer or fails with a typed ``serve/errors.py``
exception, never a hang, never a wrong value:

- **deadlines** — ``deadline_ms`` expires queued requests with
  :class:`~roc_tpu.serve.errors.ServeTimeout` at microbatch
  boundaries, so a deadline'd request resolves within ~one microbatch
  of its deadline;
- **backpressure** — the admission queue is bounded (``max_queue``);
  past it, ``submit`` sheds immediately with
  :class:`~roc_tpu.serve.errors.ServeOverload` (shed-rate in
  ``stats()``), instead of queueing unboundedly and timing everyone
  out;
- **versioned tables** — each microbatch captures ONE
  ``Predictor.published()`` table version at batch-take; an
  ``add_edges`` publish mid-flight cannot tear a batch (results carry
  ``.version``, a :class:`ServeResult` ndarray view);
- **lifecycle** — ``close()`` rejects late ``submit()`` with
  :class:`~roc_tpu.serve.errors.ServeClosed` (never a race against
  the dispatcher shutdown); ``drain()`` is the graceful half: stop
  admitting, finish everything in flight, then close — the SIGTERM
  path a replica worker takes (``serve/replica.py`` wires it to the
  PR-8 preemption guard).

Observability: the server emits a ``clock_sync`` timeline handshake at
startup (so the merged Perfetto trace gives the server process its own
aligned lane) and batches a ``serve_batch`` span per microbatch into
the same ``timeline``-category span events the trainers use — the
request pipeline renders next to the training lanes with zero new
merger code.  A ``serve`` summary event (queries, batches, latency
percentiles, shed/timeout counts) closes the session.

All counting goes through a
:class:`~roc_tpu.obs.metrics_registry.MetricsRegistry` (PR 17 — the
roc-lint ``metric-adhoc`` rule bans hand-rolled stats accumulators in
serve/), so ``stats()`` reports *current windowed* shed/error/
availability rates (``window_s``, default 60 s) next to the lifetime
totals.  Each microbatch span is stamped with the router-minted
request ids (``rids``) riding its requests plus the table version it
served, and every :class:`ServeResult` decomposes its latency into
``queue_ms`` (admission → dispatch start) vs ``device_ms`` (the
microbatch's device wall) — queue-depth pressure is visible before it
becomes shed.  ``instrument=False`` disarms registry recording and
trace stamping for overhead A/B runs (``micro_serve.py`` records both
rows; stats() is meaningless in that mode).

The request loop is a hot path under roc-lint's
``host-sync-hot-path`` rule (``analysis/ast_lint.py`` scopes
``roc_tpu/serve/`` in): the ONLY device→host sync is the result fetch
inside the predictor, which is the product.  The serve fault sites
(``resilience/inject.py serve_batch_hooks``: replica_sigkill /
replica_stall / table_swap_mid_query / serve_io) hook the dispatch
between version capture and device dispatch — the exact window the
versioned-swap drill targets.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs.events import emit
from ..obs.metrics_registry import MetricsRegistry
from ..resilience import inject
from .errors import (ServeClosed, ServeError, ServeOverload,
                     ServeTimeout)
from .predictor import Predictor

# spans accumulate and flush as ONE timeline event per this many
# microbatches (and at close) — per-batch emits would put JSONL I/O on
# the request path
_SPAN_FLUSH_EVERY = 64

# admission-queue bound (requests, not rows): past it submit() sheds
# with ServeOverload.  Sized so a saturated open-loop burst fails fast
# instead of building seconds of queueing delay.
DEFAULT_MAX_QUEUE = 1024


class ServeResult(np.ndarray):
    """The fp32 ``[n, C]`` logits, plus the table ``version`` the
    request's microbatch was served under — an ndarray view, so every
    existing consumer keeps treating results as plain arrays.  The
    trace stamps ride along: ``queue_ms`` (admission → dispatch
    start) and ``device_ms`` (the microbatch's device wall) decompose
    the request's server-side latency.  ``qmode`` is the captured
    version's quantization spec (the wire's ``res.qmode`` field reads
    it) — during a mid-rollout quant swap it says which encoding
    actually answered.  On a sharded predictor (PR 20) ``shard`` is
    the replica's owned ``(lo, hi)`` range and ``gather_ms`` the
    microbatch's cross-shard gather wall (None when every id was
    owned) — the wire's ``res.shard``/``res.gather_ms`` fields."""
    version: int = 0
    queue_ms: Optional[float] = None
    device_ms: Optional[float] = None
    qmode: str = "off"
    shard: Optional[Tuple[int, int]] = None
    gather_ms: Optional[float] = None


def _result(rows: np.ndarray, version: int,
            queue_ms: Optional[float] = None,
            device_ms: Optional[float] = None,
            qmode: str = "off",
            shard: Optional[Tuple[int, int]] = None,
            gather_ms: Optional[float] = None) -> ServeResult:
    out = rows.view(ServeResult)
    out.version = int(version)
    out.queue_ms = queue_ms
    out.device_ms = device_ms
    out.qmode = qmode
    out.shard = shard
    out.gather_ms = gather_ms
    return out


class _Req:
    """One queued request: ids, the caller's future, the absolute
    monotonic deadline (None = no deadline), the admission stamp the
    queue-delay decomposition reads, and the router-minted request id
    (``rid``) the timeline trace connects on."""

    __slots__ = ("ids", "fut", "deadline_t", "t_admit", "rid")

    def __init__(self, ids: np.ndarray, fut: Future,
                 deadline_t: Optional[float],
                 t_admit: float = 0.0,
                 rid: Optional[str] = None):
        self.ids = ids
        self.fut = fut
        self.deadline_t = deadline_t
        self.t_admit = t_admit
        self.rid = rid


class Server:
    """Coalescing dispatcher over a :class:`Predictor`.

    ``max_wait_ms`` bounds how long the dispatcher lingers after the
    first queued request to let concurrent submitters join the batch
    (0 = dispatch immediately; the default 0.2 ms trades ~a fifth of a
    millisecond of p50 for a much fatter microbatch under load).
    ``max_queue`` bounds the admission queue (see module docstring);
    ``default_deadline_ms`` applies to submits that pass none."""

    def __init__(self, predictor: Predictor,
                 max_wait_ms: float = 0.2,
                 name: str = "serve",
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 default_deadline_ms: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 instrument: bool = True,
                 stats_window_s: float = 60.0):
        self.pred = predictor
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.name = name
        self.max_queue = int(max_queue)
        self.default_deadline_ms = default_deadline_ms
        self.stats_window_s = float(stats_window_s)
        self._lock = threading.Condition()
        self._queue: List[_Req] = []
        self._closed = False
        self._draining = False
        self._dispatching = False
        self._spans: List[Tuple[str, float, float, Dict[str, Any]]] = []
        # ALL counting goes through the registry (windowed rates +
        # lifetime totals from one recording); instrument=False
        # disarms it for overhead A/B runs
        self._obs = bool(instrument)
        self.reg = (registry if registry is not None
                    else MetricsRegistry(f"server:{name}"))
        self._c_accepted = self.reg.counter("accepted")
        self._c_shed = self.reg.counter("shed")
        self._c_timeout = self.reg.counter("timeout")
        self._c_rejected = self.reg.counter("rejected_closed")
        self._c_errors = self.reg.counter("errors")
        self._c_ok = self.reg.counter("ok")
        self._c_batches = self.reg.counter("batches")
        self._c_rows = self.reg.counter("rows")
        self._h_batch = self.reg.histogram("batch_ms")
        self._h_queue = self.reg.histogram("queue_ms")
        self._h_gather = self.reg.histogram("gather_ms")
        self._batch_seq = 0
        self._versions = set()       # table versions actually served
        # the lane handshake: wall/mono stamped by the bus — the
        # timeline merger aligns this process's spans on it
        emit("timeline", f"clock_sync: serve server '{name}' up "
             f"(backend={predictor.backend})", console=False,
             kind="clock_sync", server=name)
        self._thread = threading.Thread(target=self._loop,
                                        name=f"serve:{name}",
                                        daemon=True)
        self._thread.start()

    # ---------------------------------------------------------- public

    def submit(self, node_ids,
               deadline_ms: Optional[float] = None,
               rid: Optional[str] = None) -> Future:
        """Queue a query; the returned future resolves to the fp32
        ``[len(node_ids), C]`` logits (a :class:`ServeResult` carrying
        the table ``version`` it was served under), or to one of the
        typed ``serve/errors.py`` failures — never a bare hang.
        ``rid`` is the router-minted request id the timeline trace
        connects on (stamped into this request's microbatch span)."""
        ids = np.asarray(node_ids, dtype=np.int32).ravel()
        fut: Future = Future()
        if ids.size and (ids.min() < 0
                         or ids.max() >= self.pred.num_nodes):
            fut.set_exception(ValueError(
                f"node ids out of range [0, {self.pred.num_nodes})"))
            return fut
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        now = time.monotonic()
        deadline_t = (None if deadline_ms is None
                      else now + max(0.0, deadline_ms) / 1e3)
        with self._lock:
            if self._closed or self._draining:
                if self._obs:
                    self._c_rejected.inc()
                fut.set_exception(ServeClosed(
                    f"server '{self.name}' is "
                    + ("draining" if self._draining and not self._closed
                       else "closed")))
                return fut
            if len(self._queue) >= self.max_queue:
                if self._obs:
                    self._c_shed.inc()
                fut.set_exception(ServeOverload(
                    f"admission queue full ({self.max_queue} queued) "
                    f"— load shed"))
                return fut
            self._queue.append(_Req(ids, fut, deadline_t,
                                    t_admit=now, rid=rid))
            if self._obs:
                self._c_accepted.inc()
            self._lock.notify()
        return fut

    def query(self, node_ids,
              deadline_ms: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(node_ids, deadline_ms=deadline_ms).result()

    def stats(self) -> Dict[str, Any]:
        """Microbatch + robustness accounting.  The ``n_*`` keys are
        lifetime totals; ``shed_rate``/``error_rate``/``availability``
        are *windowed* — computed over the trailing ``window_s``
        seconds from the metrics registry, so a recovered server
        reports its current health, not its whole history (``None``
        when the window saw no admissions).  Latency quantiles come
        from the registry's log-bucket histograms (within one bucket,
        ~16%% relative, of exact)."""
        w = self.stats_window_s    # already float-coerced in __init__
        n_queries = self._c_accepted.total
        n_shed = self._c_shed.total
        n_timeout = self._c_timeout.total
        n_rejected = self._c_rejected.total
        n_errors = self._c_errors.total
        n_ok = self._c_ok.total
        n_batches = self._c_batches.total
        n_rows = self._c_rows.total
        w_shed = self._c_shed.sum_over(w)
        w_denom = (self._c_accepted.sum_over(w) + w_shed
                   + self._c_rejected.sum_over(w))
        w_bad = self._c_timeout.sum_over(w) + self._c_errors.sum_over(w)
        w_ok = self._c_ok.sum_over(w)
        with self._lock:
            versions = sorted(self._versions)

        def rate(num: int) -> Optional[float]:
            return round(num / w_denom, 4) if w_denom > 0 else None

        def q(h, p: float, window: Optional[float] = None
              ) -> Optional[float]:
            v = h.quantile(p, window)
            return round(v, 4) if v is not None else None

        return {"n_queries": n_queries,
                "n_batches": n_batches,
                "rows_per_batch": (round(n_rows / n_batches, 2)
                                   if n_batches else None),
                "batch_p50_ms": q(self._h_batch, 0.50),
                "batch_p99_ms": q(self._h_batch, 0.99),
                "queue_p50_ms": q(self._h_queue, 0.50),
                "gather_p50_ms": q(self._h_gather, 0.50),
                "n_shed": n_shed,
                "n_timeout": n_timeout,
                "n_rejected_closed": n_rejected,
                "n_errors": n_errors,
                "n_ok": n_ok,
                "window_s": w,
                "shed_rate": rate(w_shed),
                "error_rate": rate(w_bad),
                "availability": rate(w_ok),
                "table_versions": versions[-8:],
                }

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Graceful shutdown, the SIGTERM path: stop admitting (late
        submits fail typed ``ServeClosed``), let the dispatcher finish
        every already-accepted request, then close.  Returns True when
        everything in flight completed within ``timeout``."""
        with self._lock:
            if self._closed:
                return True
            self._draining = True
            self._lock.notify_all()
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while self._queue or self._dispatching:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    break
                self._lock.wait(timeout=left)
            drained = not self._queue and not self._dispatching
        emit("serve", f"server '{self.name}' drained "
             f"({'clean' if drained else 'TIMED OUT with work left'})",
             console=False, kind="drain", clean=drained)
        self.close()
        return drained

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._lock.notify_all()
        self._thread.join(timeout=10.0)
        self._flush_spans(final=True)
        s = self.stats()
        emit("serve", f"server '{self.name}' closed: "
             f"{s['n_queries']} queries in {s['n_batches']} batches "
             f"(p50 {s['batch_p50_ms']} ms, shed {s['n_shed']}, "
             f"timeout {s['n_timeout']})", console=False,
             kind="summary", **s)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- dispatcher

    def _expire_locked(self, now: float) -> List[_Req]:
        """Split deadline-expired entries out of the queue (holding
        the lock); the CALLER completes their futures outside it — a
        done-callback may re-enter ``submit`` and the condition's lock
        is not reentrant."""
        if not any(r.deadline_t is not None and r.deadline_t <= now
                   for r in self._queue):
            return []
        live: List[_Req] = []
        dead: List[_Req] = []
        for r in self._queue:
            if r.deadline_t is not None and r.deadline_t <= now:
                dead.append(r)
            else:
                live.append(r)
        self._queue = live
        return dead

    def _fail_timeouts(self, dead: List[_Req]) -> None:
        """Complete expired futures OUTSIDE the lock (done-callbacks
        may re-enter submit); the registry counter has its own lock,
        so counting here keeps it off submit()'s wait path too."""
        if dead and self._obs:
            self._c_timeout.inc(len(dead))
        for r in dead:
            if not r.fut.done():
                r.fut.set_exception(ServeTimeout(
                    "deadline expired before dispatch "
                    "(queued behind a full microbatch)"))

    def _take_batch(self) -> Optional[List[_Req]]:
        """Block for work; after the first request, linger up to
        ``max_wait_s`` so concurrent submitters coalesce.  Expires
        deadline'd entries at every boundary (never dispatches one).
        Returns None at shutdown."""
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._lock.wait()
                dead = self._expire_locked(time.monotonic())
                have = bool(self._queue)
                closed = self._closed
            self._fail_timeouts(dead)
            if not have:
                if closed:
                    return None
                continue    # everything queued had expired; re-wait
            if self.max_wait_s > 0:
                deadline = time.monotonic() + self.max_wait_s
                cap = max(self.pred.buckets)
                while time.monotonic() < deadline:
                    with self._lock:
                        if (sum(r.ids.size for r in self._queue) >= cap
                                or self._closed or self._draining):
                            break
                    time.sleep(self.max_wait_s / 8.0)
            with self._lock:
                dead = self._expire_locked(time.monotonic())
                batch, self._queue = self._queue, []
                if batch:
                    self._dispatching = True
            self._fail_timeouts(dead)
            if batch:
                return batch
            # the linger expired everything it was waiting on — re-wait

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 - fail the futures
                if self._obs:
                    self._c_errors.inc(len(batch))
                # the typed-failure contract covers dispatch errors
                # too: wrap foreign exceptions in ServeError, chained
                # so the replica wire (and post-mortems) can still
                # see the underlying class (serve_io's retryable
                # OSError rides __cause__)
                exc: Exception = e
                if not isinstance(e, (ServeError, ValueError)):
                    exc = ServeError(
                        f"dispatch failed: {type(e).__name__}: {e}")
                    exc.__cause__ = e
                for r in batch:
                    if not r.fut.done():
                        r.fut.set_exception(exc)
            finally:
                with self._lock:
                    self._dispatching = False
                    self._lock.notify_all()

    def _dispatch(self, batch: List[_Req]) -> None:
        ids = (np.concatenate([r.ids for r in batch])
               if len(batch) > 1 else batch[0].ids)
        with self._lock:
            self._batch_seq += 1
            batch_no = self._batch_seq
        # ONE consistent table version for the whole microbatch,
        # captured BEFORE the fault hooks: the table_swap_mid_query
        # drill publishes a new version right here, and this batch
        # must still finish bit-exact on `pub`
        pub = self.pred.published()
        inject.serve_batch_hooks(self, batch_no)
        t0 = time.monotonic()
        rows = self.pred.query(ids, pub=pub)
        ms = (time.monotonic() - t0) * 1e3
        # cross-shard gather wall for this microbatch (None when every
        # id was owned, and always on full-table predictors)
        gms = getattr(self.pred, "last_gather_ms", None)
        shard = getattr(self.pred, "shard", None)
        # the device dispatch above runs UNLOCKED; registry metrics
        # carry their own fine-grained locks, so only the version set
        # and span buffer hold the server lock, and the span flush
        # emits after release — an emit under the lock would put JSONL
        # I/O on submit()'s wait path (roc-lint blocking-under-lock)
        if self._obs:
            self._h_batch.record(ms)
            if gms is not None:
                self._h_gather.record(gms)
            self._c_batches.inc()
            self._c_rows.inc(int(ids.size))
            self._c_ok.inc(len(batch))
            for r in batch:
                self._h_queue.record(max(0.0, (t0 - r.t_admit) * 1e3))
        rids = sorted({r.rid for r in batch if r.rid is not None})
        args: Dict[str, Any] = {"batch": batch_no,
                                "rows": int(ids.size),
                                "version": int(pub.version)}
        if rids:
            args["rids"] = rids
        with self._lock:
            self._versions.add(int(pub.version))
            self._spans.append(("serve_batch", t0, ms, args))
            flush = len(self._spans) >= _SPAN_FLUSH_EVERY
        if flush:
            self._flush_spans()
        lo = 0
        for r in batch:
            if not r.fut.done():
                qms = max(0.0, (t0 - r.t_admit) * 1e3)
                r.fut.set_result(
                    _result(rows[lo:lo + r.ids.size], pub.version,
                            queue_ms=round(qms, 3),
                            device_ms=round(ms, 3),
                            qmode=pub.qmode, shard=shard,
                            gather_ms=(None if gms is None
                                       else round(gms, 3))))
            lo += r.ids.size

    def _flush_spans(self, final: bool = False) -> None:
        with self._lock:
            spans, self._spans = self._spans, []
        if not spans:
            return
        emit("timeline",
             f"spans: {len(spans)} microbatch(es)"
             + (" (final)" if final else ""), console=False,
             kind="spans",
             spans=[[n, round(t0, 6), round(ms, 3), args]
                    for n, t0, ms, args in spans])
