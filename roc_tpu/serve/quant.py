"""Quantized serving tables: symmetric per-row int8/fp8 with scales.

The serving money is in table HBM — every replica of the precomputed
backend carries a full fp32 ``[V, F]`` propagation table, which caps
graph size at one replica's memory.  This module is the quantization
layer the whole serve tier shares:

- **Scheme**: symmetric per-row.  ``scale[r] = amax(|x[r]|) / Q`` and
  ``q[r] = clip(rint(x[r] / scale[r]), -Q, Q)`` with ``Q = 127`` for
  int8 (fp8-e4m3 stores the scaled row directly; its ``Q`` is the
  format's finite max, 448).  Per-row beats per-tensor on propagation
  tables because hub rows after ``S^k`` aggregation have orders of
  magnitude more mass than leaves — one shared scale would crush the
  leaves to zero.
- **Round-trip identity** (the property cold start leans on): the max
  element of a row maps to exactly ±Q, so re-deriving the scale from
  the DEquantized row reproduces the original scale to ~1 ulp and
  ``rint`` recovers every ``q`` exactly.  Hence
  ``quantize(dequantize(quantize(x))) == quantize(x)`` bit-for-bit —
  an artifact that persists ``(q, scale)`` can rebuild the exact
  device table with no fp32 master copy and ZERO new compiles
  (tests/test_serve_quant.py pins this).
- **Dequant-in-register**: the serve matmul gathers int8 rows and
  multiplies by the gathered scales inside the jitted program
  (``Predictor._serve_step``) — the full fp32 table is NEVER
  materialized on device (the ``dequant-hot-path`` roc-lint rule
  makes that a machine-checked invariant of ``roc_tpu/serve/``).
- **Drift gate**: quantization is lossy, so export measures argmax
  agreement and max |Δlogit| against the fp32 reference on a held-out
  node sample and REFUSES (:class:`QuantDriftError`) past the
  thresholds — loudly, the way fingerprint mismatches already refuse.
  After an ``add_edges`` invalidation the refreshed rows re-check
  against the scale envelope recorded at build (quantization error is
  bounded by ``scale/2`` per element, so a row whose dynamic range
  exploded is caught BEFORE its version publishes).

int8 is the portable floor; fp8-e4m3 rides where the jax/ml_dtypes
pair supports it (:func:`fp8_supported`) and persists as a uint8 byte
view because ``np.load`` cannot round-trip the ml_dtypes dtype.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

QMODES = ("off", "int8", "fp8")

INT8_QMAX = 127.0
FP8_QMAX = 448.0          # float8_e4m3fn finite max

# drift-gate defaults: the export CLI/--quantize arm overrides them.
# The Δlogit bound is RELATIVE to the reference logit magnitude
# (max |Δ| / max(1, max |ref|)) — an absolute bound would bite or
# slumber depending on the head's output scale; per-row int8 lands at
# ~0.5-0.8% relative on the rig configs, so 2% is a real gate with
# real headroom, at any logit scale
DRIFT_ARGMAX_MIN = 0.99   # fraction of sampled nodes with equal argmax
DRIFT_DLOGIT_MAX = 0.02   # relative max |q_logit - fp32_logit|
DRIFT_SAMPLE = 512        # held-out node sample size (deterministic)

# scale-envelope slack for post-invalidation re-checks: a refreshed
# row may legitimately grow (new edges add mass), but a row whose
# quantization step jumps past ``envelope * slack`` serves visibly
# coarser values than anything the export-time drift gate measured
SCALE_GUARD_SLACK = 4.0


class QuantDriftError(RuntimeError):
    """Quantized serving would drift past the gate — export refuses to
    write the artifact; invalidation refuses to publish the version."""


class QuantSpec(NamedTuple):
    """The serialized quantization contract an artifact carries."""
    mode: str                     # "off" | "int8" | "fp8"
    scheme: str = "symmetric-per-row"

    def to_json(self) -> Dict[str, Any]:
        return {"mode": self.mode, "scheme": self.scheme}

    @classmethod
    def from_json(cls, d: Optional[Dict[str, Any]]) -> "QuantSpec":
        if not d:
            return cls("off")
        return cls(str(d.get("mode", "off")),
                   str(d.get("scheme", "symmetric-per-row")))


def check_mode(mode: str) -> str:
    if mode not in QMODES:
        raise ValueError(f"unknown quant mode {mode!r}; have {QMODES}")
    if mode == "fp8" and not fp8_supported():
        raise ValueError(
            "quant mode 'fp8' needs jax.numpy.float8_e4m3fn + "
            "ml_dtypes — unavailable in this environment; int8 is "
            "the portable floor")
    return mode


def fp8_supported() -> bool:
    """fp8-e4m3 availability: the jnp dtype AND the ml_dtypes numpy
    side (persistence + host dequant) must both exist."""
    try:
        import jax.numpy as jnp
        import ml_dtypes
        return hasattr(jnp, "float8_e4m3fn") \
            and hasattr(ml_dtypes, "float8_e4m3fn")
    except Exception:
        return False


def _fp8_np_dtype():
    import ml_dtypes
    return np.dtype(ml_dtypes.float8_e4m3fn)


def storage_dtype(mode: str):
    """The on-disk / on-device storage dtype of one quantized table."""
    if mode == "int8":
        return np.dtype(np.int8)
    if mode == "fp8":
        return _fp8_np_dtype()
    raise ValueError(f"no storage dtype for quant mode {mode!r}")


def qmax_of(mode: str) -> float:
    return INT8_QMAX if mode == "int8" else FP8_QMAX


# -------------------------------------------------------- core codec

def row_scales(x: np.ndarray, mode: str) -> np.ndarray:
    """fp32 ``[V]`` per-row scales; all-zero rows get scale 1.0 so the
    codec never divides by zero (their q rows are exactly zero)."""
    amax = np.max(np.abs(np.asarray(x, dtype=np.float32)), axis=1)
    scale = amax / qmax_of(mode)
    scale[scale == 0.0] = 1.0
    return scale.astype(np.float32)


def quantize_rows(x: np.ndarray, mode: str,
                  scale: Optional[np.ndarray] = None
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """``(q, scale)`` for an fp32 ``[V, F]`` table.  ``scale`` may be
    supplied to re-encode under a pinned envelope (refresh paths pass
    None and re-derive — the round-trip identity needs the derived
    scale)."""
    x = np.asarray(x, dtype=np.float32)
    if x.ndim != 2:
        raise ValueError(f"quantize_rows wants [V, F], got {x.shape}")
    if scale is None:
        scale = row_scales(x, mode)
    scaled = x / scale[:, None]
    if mode == "int8":
        q = np.clip(np.rint(scaled), -INT8_QMAX,
                    INT8_QMAX).astype(np.int8)
    elif mode == "fp8":
        q = scaled.astype(_fp8_np_dtype())
    else:
        raise ValueError(f"cannot quantize to mode {mode!r}")
    return q, scale


def dequantize_rows(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Host-side fp32 reconstruction (build/persistence paths only —
    the device hot path dequantizes gathered rows in-register)."""
    return (np.asarray(q, dtype=np.float32)
            * np.asarray(scale, dtype=np.float32)[:, None])


# ---------------------------------------------------- persistence aid

def to_storage_bytes(q: np.ndarray) -> np.ndarray:
    """npz-safe view of a quantized payload: fp8 goes through uint8
    (``np.load`` reads ml_dtypes arrays back as void); int8 is already
    npz-native but takes the same path for one load-side rule."""
    return q.view(np.uint8)


def from_storage_bytes(raw: np.ndarray, mode: str) -> np.ndarray:
    return np.asarray(raw, dtype=np.uint8).view(storage_dtype(mode))


# ----------------------------------------------------------- params

PARAMS_SCALE_SUFFIX = "::scale"


def quantize_params(host_params: Dict[str, np.ndarray], mode: str
                    ) -> Tuple[Dict[str, np.ndarray],
                               Dict[str, np.ndarray], List[str]]:
    """Per-row quantization of the exportable param dict: every ≥2-D
    float leaf quantizes along its leading axis (weights; a companion
    ``<key>::scale`` entry carries the scales), everything else —
    biases, 1-D norms, integer leaves — stays verbatim.  Returns
    ``(store, roundtrip, quantized_keys)``: ``store`` is what
    ``params.npz`` persists, ``roundtrip`` the dequantized params the
    EXPORT-TIME predictor must serve with so export and cold load are
    value-identical (the fingerprint is structural — shapes/dtypes —
    and both sides keep the original structure)."""
    store: Dict[str, np.ndarray] = {}
    roundtrip: Dict[str, np.ndarray] = {}
    qkeys: List[str] = []
    for k, v in host_params.items():
        v = np.asarray(v)
        if v.ndim >= 2 and np.issubdtype(v.dtype, np.floating):
            mat = v.reshape(v.shape[0], -1).astype(np.float32)
            q, sc = quantize_rows(mat, mode)
            store[k] = to_storage_bytes(q).reshape(v.shape)
            store[k + PARAMS_SCALE_SUFFIX] = sc
            roundtrip[k] = dequantize_rows(q, sc) \
                .reshape(v.shape).astype(v.dtype)
            qkeys.append(k)
        else:
            store[k] = v
            roundtrip[k] = v
    return store, roundtrip, qkeys


def dequantize_params(raw: Dict[str, np.ndarray], mode: str
                      ) -> Dict[str, np.ndarray]:
    """Inverse of :func:`quantize_params` for a loaded ``params.npz``
    dict (storage-byte views + ``::scale`` companions → fp32)."""
    out: Dict[str, np.ndarray] = {}
    for k, v in raw.items():
        if k.endswith(PARAMS_SCALE_SUFFIX):
            continue
        sk = k + PARAMS_SCALE_SUFFIX
        if sk in raw:
            q = from_storage_bytes(
                np.asarray(v).reshape(v.shape[0], -1), mode)
            out[k] = dequantize_rows(q, raw[sk]) \
                .reshape(v.shape).astype(np.float32)
        else:
            out[k] = v
    return out


# ------------------------------------------------------- measurement

def table_bytes(shape: Tuple[int, int], mode: str) -> int:
    """Device/disk bytes of ONE [V, F] table under ``mode`` (quantized
    modes carry their fp32 per-row scale vector)."""
    v, f = int(shape[0]), int(shape[1])
    if mode == "off":
        return v * f * 4
    return v * f * storage_dtype(mode).itemsize + v * 4


def scale_stats(scale: np.ndarray) -> Dict[str, float]:
    # host numpy on export-time scale vectors — no device round trip
    s = np.asarray(scale, dtype=np.float64)
    return {"min": round(float(s.min()), 8),  # roc-lint: ok=host-sync-hot-path
            "max": round(float(s.max()), 8),  # roc-lint: ok=host-sync-hot-path
            "mean": round(float(s.mean()), 8)}  # roc-lint: ok=host-sync-hot-path


def drift_report(ref_logits: np.ndarray, q_logits: np.ndarray,
                 argmax_min: float = DRIFT_ARGMAX_MIN,
                 dlogit_max: float = DRIFT_DLOGIT_MAX
                 ) -> Dict[str, Any]:
    """Measured accuracy drift of the quantized path vs the fp32
    reference on one node sample: argmax agreement + max |Δlogit|,
    with the pass/fail verdict against the thresholds."""
    ref = np.asarray(ref_logits, dtype=np.float32)
    got = np.asarray(q_logits, dtype=np.float32)
    if ref.shape != got.shape:
        raise ValueError(f"drift shapes differ: {ref.shape} vs "
                         f"{got.shape}")
    # host numpy over the already-fetched gate sample — export-time
    # measurement, not a request-path sync
    n = max(ref.shape[0], 1)
    agree, dmax, refmax = 1.0, 0.0, 0.0
    if ref.size:
        eq = ref.argmax(axis=1) == got.argmax(axis=1)
        agree = float(np.mean(eq))  # roc-lint: ok=host-sync-hot-path
        dmax = float(np.abs(ref - got).max())  # roc-lint: ok=host-sync-hot-path
        refmax = float(np.abs(ref).max())  # roc-lint: ok=host-sync-hot-path
    rel = dmax / max(1.0, refmax)
    return {"sample": int(n),
            "argmax_agreement": round(agree, 6),
            "max_abs_dlogit": round(dmax, 6),
            "ref_max_logit": round(refmax, 6),
            "rel_dlogit": round(rel, 6),
            "argmax_min": argmax_min,
            "dlogit_max": dlogit_max,
            "ok": bool(agree >= argmax_min and rel <= dlogit_max)}


def require_drift_ok(report: Dict[str, Any], where: str) -> None:
    """The refusal: a failed gate raises with the full measurement in
    the message (the fingerprint-mismatch idiom — loud, actionable,
    and BEFORE any artifact/version becomes visible)."""
    if not report.get("ok"):
        raise QuantDriftError(
            f"{where}: quantization drift gate FAILED — argmax "
            f"agreement {report['argmax_agreement']} (need >= "
            f"{report['argmax_min']}), relative max |dlogit| "
            f"{report['rel_dlogit']} (need <= {report['dlogit_max']}; "
            f"abs {report['max_abs_dlogit']} on ref magnitude "
            f"{report['ref_max_logit']}) on {report['sample']} "
            f"sampled node(s); export/serve fp32 or relax the "
            f"thresholds deliberately")


def drift_sample(num_nodes: int, n: int = DRIFT_SAMPLE,
                 seed: int = 0) -> np.ndarray:
    """The held-out node sample, deterministic per (V, n, seed) so
    export and any later re-check measure the same rows."""
    rng = np.random.RandomState(seed)
    n = min(int(n), int(num_nodes))
    return np.sort(rng.choice(num_nodes, size=n,
                              replace=False)).astype(np.int32)


# ----------------------------------------------------- capture hook

class QuantizingCapture:
    """A ``stream_prefix_to_host`` capture sink that quantizes each
    stage table AS IT STREAMS (``core/streaming.py`` hands the sink
    exclusively-owned arrays, so the fp32 stage can be dropped the
    moment its ``(q, scale)`` pair is taken): the >RAM export path —
    host peak holds ONE fp32 stage instead of all k.

    ``keep_fp32_last=True`` additionally retains the final stage in
    fp32 (the serve table builders want it for the drift reference)."""

    def __init__(self, mode: str, keep_fp32_last: bool = False):
        self.mode = check_mode(mode)
        if self.mode == "off":
            raise ValueError("QuantizingCapture needs a quantized "
                             "mode; pass a plain list for fp32")
        self.keep_fp32_last = keep_fp32_last
        self.stages: list = []          # (q, scale) per stage
        self.last_fp32: Optional[np.ndarray] = None

    def append(self, x: np.ndarray) -> None:
        self.stages.append(quantize_rows(x, self.mode))
        if self.keep_fp32_last:
            self.last_fp32 = x

    def dequantized(self) -> list:
        return [dequantize_rows(q, s) for q, s in self.stages]
