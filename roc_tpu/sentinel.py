"""``python -m roc_tpu.sentinel`` — perf-regression gate over the
BENCH_*.json trajectory (and a live run's metrics JSONL).

Thin packaged entry point over :mod:`roc_tpu.obs.sentinel` (which is
stdlib-only and also runs as a plain script on a box without jax:
``python roc_tpu/obs/sentinel.py ...``).  Exits nonzero on a
regression beyond noise; ``--json`` prints one machine-readable line
for CI and the bench probe preflight.
"""

from __future__ import annotations

import sys

from .obs.sentinel import (bench_history, bench_verdict,  # noqa: F401
                           check_run, detect, main, metrics_summary)

if __name__ == "__main__":
    sys.exit(main())
