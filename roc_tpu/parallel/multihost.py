"""Multi-host (DCN x ICI) distributed runtime.

The reference runs multi-machine through Legion address spaces over
GASNet (``Makefile:26``) with NCCL linked for collectives
(``nccl_task.cu:19-38``; the multi-rank init is dead-coded,
``gnn.cc:630-642``) and a mapper that round-robins partitions across
machines first (``gnn_mapper.cc:120-131``).  The TPU-native
equivalents here:

- :func:`init_distributed` — ``jax.distributed.initialize`` wrapper
  (the NCCL-communicator/GASNet bootstrap analog); env-driven so the
  same entry point works under any launcher.
- :func:`make_parts_mesh` — a 1-D ``'parts'`` mesh laid out so that
  consecutive partitions land on the same host: the ring/all-gather
  halo then crosses DCN only ``num_hosts`` times per rotation instead
  of every hop (the mapper's machine-first round-robin solved the
  inverse problem — here locality, not spread, minimizes the slow
  link).
- :func:`process_local_parts` / :func:`make_sharded_array` — each host
  materializes only its own partitions' rows and the global jax.Array
  is assembled from per-process local shards
  (``jax.make_array_from_single_device_arrays``) — the analog of the
  reference's per-partition loader tasks running on each node's CPUs
  (``load_task.cu:201-269``) rather than one host broadcasting.

Single-process (including the 8-virtual-device CPU test rig) is the
degenerate case throughout; nothing here requires real multi-host
hardware to compile or test.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import PARTS_AXIS


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None
                     ) -> None:
    """Initialize the JAX distributed runtime (multi-host DCN).

    Arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``), so launchers only need to export those.  A
    no-op when single-process (no coordinator configured) — the
    single-host paths then work unchanged.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return
    platforms = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS") or "")
    if "cpu" in platforms or not platforms:
        # XLA:CPU's default in-process collectives cannot cross
        # address spaces ("Multiprocess computations aren't
        # implemented on the CPU backend") — multi-process CPU runs
        # (the 2-process DCN parity tests, loopback rehearsals of pod
        # topologies) need the Gloo transport selected BEFORE the
        # backend initializes.  Armed too when the platform is
        # auto-detected (empty): the flag only shapes the CPU client,
        # which accelerator-backend collectives never route through,
        # so TPU/GPU pods are unaffected; an EXPLICIT non-cpu platform
        # list skips it.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # noqa: BLE001 - flag renamed across jax
            pass
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0"))
    # pin the event-clock identity the moment the process id is known:
    # trainer-setup events (partition stats, plan echoes) fire BEFORE
    # the run manifest's own set_clock_identity, and a launcher that
    # passes process_id programmatically (this function's argv path)
    # never exported JAX_PROCESS_ID — without this, every process's
    # early events would stamp proc=0 and mis-lane in the merged
    # timeline
    from ..obs.events import set_clock_identity
    set_clock_identity(proc=process_id)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)


def checkpoint_commit_barrier(tag: str) -> None:
    """The checkpoint-v3 two-phase-commit rendezvous: every process
    has renamed its shard files into place; after this barrier,
    process 0 publishes MANIFEST.json (utils/checkpoint.
    write_snapshot).  Only reached when MORE than one process owns
    shards — today's fully replicated state (process 0 owns
    everything) never needs it, so the degenerate path stays
    barrier-free exactly like the v2 single-writer handshake.
    Single-process is a no-op.  A dead peer wedges the survivors
    here; the heartbeat dates the stall and — with
    ``ROC_TPU_STALL_TIMEOUT_S`` armed — promotes it into a
    StallFailure the recovery loop can checkpoint-restart
    (obs/heartbeat.py), the same contract as the setup collectives
    above."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    from ..obs.heartbeat import Heartbeat
    with Heartbeat("ckpt_commit_barrier", op=tag):
        multihost_utils.sync_global_devices(f"roc_tpu:ckpt:{tag}")


def make_parts_mesh(num_parts: Optional[int] = None,
                    devices: Optional[List] = None,
                    model: int = 1) -> Mesh:
    """``'parts'`` (or 2-D ``('parts', 'model')`` when ``model > 1``)
    mesh across all processes' devices — alias of
    :func:`roc_tpu.parallel.distributed.make_mesh` (one constructor,
    one partition->device layout; see its docstring for the DCN
    locality invariant).  The model axis is the FAST axis of the
    device order, so a partition's model group stays within one
    host's ICI domain whenever the host owns ``model`` consecutive
    devices."""
    from .distributed import make_mesh
    return make_mesh(num_parts, devices, model=model)


def _part_device_rows(mesh: Mesh) -> np.ndarray:
    """Mesh devices as a ``[parts, model]`` grid (model = 1 for the
    1-D mesh) — row ``p`` holds every device that carries partition
    ``p``'s ``P('parts')`` shard (replicated over the model axis)."""
    return mesh.devices.reshape(mesh.devices.shape[0], -1)


def process_local_parts(mesh: Mesh) -> List[int]:
    """Partition indices with at least one device on this process —
    the set of shards this host must load (the reference's per-node
    loader tasks, ``load_task.cu:201-269``, selected by the mapper;
    here selected by mesh placement).  On a 2-D mesh a partition is
    local when ANY of its model-axis devices is."""
    pid = jax.process_index()
    return [i for i, row in enumerate(_part_device_rows(mesh))
            if any(d.process_index == pid for d in row)]


def make_sharded_array(mesh: Mesh, local_parts: List[int],
                       local_shards: Sequence[np.ndarray],
                       global_shape: Tuple[int, ...]) -> jax.Array:
    """Assemble a ``P('parts')``-sharded global array from this
    process's shard data only (no cross-host broadcast).

    local_shards[i] is the [1, ...] slice for partition
    ``local_parts[i]``.  On a single process this reduces to a plain
    ``device_put`` of the stacked array.  On a 2-D mesh each
    partition's shard is replicated onto every addressable device of
    its model row — the data axes never shard over ``model``.
    """
    sharding = NamedSharding(mesh, P(PARTS_AXIS))
    rows = _part_device_rows(mesh)
    pid = jax.process_index()
    singles = []
    for part, shard in zip(local_parts, local_shards):
        arr = np.ascontiguousarray(shard)
        for d in rows[part]:
            if d.process_index == pid:
                singles.append(jax.device_put(arr, d))
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, singles)


def _allreduce_part_vec_max(mesh: Mesh, local: List[int],
                            vecs: dict) -> np.ndarray:
    """Elementwise max over per-partition int vectors across all hosts
    (each host knows only its own parts' vectors) — O(P * len) tiny
    collective.  Single-process short-circuits."""
    if jax.process_count() == 1:
        return np.max(np.stack([vecs[p] for p in local]), axis=0)
    import jax.numpy as jnp
    num_parts = int(mesh.devices.shape[0])
    width = len(next(iter(vecs.values())))
    arr = make_sharded_array(
        mesh, local,
        [np.asarray(vecs[p], dtype=np.int64)[None] for p in local],
        (num_parts, width))
    # one-shot bootstrap collective at table build, not a training
    # step — compile telemetry would be noise: roc-lint: ok=bare-jit
    reduce = jax.jit(lambda a: jnp.max(a, axis=0),
                     out_shardings=NamedSharding(mesh, P()))
    # a peer process that died before this DCN rendezvous hangs every
    # survivor here forever; the watchdog dates the stall and — with
    # ROC_TPU_STALL_TIMEOUT_S armed — converts it into a StallFailure
    # the recovery loop can checkpoint-restart (obs/heartbeat.py)
    from ..obs.heartbeat import Heartbeat
    with Heartbeat("multihost_collective", op="part_vec_max"):
        return np.asarray(reduce(arr))


def _allreduce_part_stats(mesh: Mesh, local: List[int],
                          stats: dict) -> Tuple[int, int]:
    """(global max of stat[0], global sum of stat[1]) over all
    partitions, where each host knows only its own parts' values.
    Single-process short-circuits; multi-host runs one tiny [P, 2]
    collective — the O(P) agreement that replaces a whole-graph pass.
    """
    if jax.process_count() == 1:
        return (max(v[0] for v in stats.values()),
                sum(v[1] for v in stats.values()))
    import jax.numpy as jnp
    num_parts = int(mesh.devices.shape[0])
    arr = make_sharded_array(
        mesh, local,
        [np.asarray([[stats[p][0], stats[p][1]]], dtype=np.int64)
         for p in local],
        (num_parts, 2))
    # one-shot bootstrap collective — roc-lint: ok=bare-jit
    reduce = jax.jit(
        lambda a: jnp.stack([jnp.max(a[:, 0]), jnp.sum(a[:, 1])]),
        out_shardings=NamedSharding(mesh, P()))
    # same DCN-rendezvous hazard (and the same deadline promotion) as
    # _allreduce_part_vec_max above
    from ..obs.heartbeat import Heartbeat
    with Heartbeat("multihost_collective", op="part_stats"):
        out = np.asarray(reduce(arr))
    return int(out[0]), int(out[1])


def shard_dataset_local(dataset, pg, mesh: Mesh, dtype=None,
                        aggr_impl: str = "segment",
                        halo: str = "gather",
                        section_rows: Optional[int] = None,
                        sect_sub_w: int = 8, sect_u16: bool = False,
                        bdense_min_fill: int = 64,
                        bdense_a_budget: Optional[int] = 2 << 30,
                        bdense_group: int = 1):
    """Multi-host version of ``distributed.shard_dataset``: each process
    BUILDS and uploads only its own partitions' shards — row-sliced
    loads via :class:`roc_tpu.core.source.DataSource`, per-partition
    column fills, per-partition ELL tables against a degree-derived
    global shape plan.  No whole-graph O(E) materialization per
    host beyond the O(V) row-pointer metadata (the reference's
    per-partition loader tasks, ``load_task.cu:41-51,201-245``).
    Returns the same ``ShardedData`` so ``DistributedTrainer`` works
    unchanged.

    ``dataset`` may be a Dataset (in-memory; slices are views) or any
    DataSource (e.g. ``FileSource`` for the on-disk reference layout).
    ``pg`` may be a PartitionPlan — column data is only read for local
    parts.  ``halo='ring'`` is partition-local too: per-part pair
    lists from local column reads, with the uniform pair width agreed
    via an O(P) collective (never a whole-graph pass).
    ``aggr_impl='bdense'`` agrees the uniform per-part block count and
    the residual sectioned chunk plan the same O(P) way.
    """
    import jax.numpy as jnp
    from ..core.ell import build_ell, ell_shape_plan, place_ell_part
    from ..core.graph import MASK_NONE
    from ..core.partition import partition_col
    from ..core.source import as_source
    from .distributed import ShardedData, remap_col_to_padded

    if dtype is None:
        dtype = jnp.float32
    src = as_source(dataset)
    local = process_local_parts(mesh)
    P, pn, pe = pg.num_parts, pg.part_nodes, pg.part_edges

    def put_parts(build, shape, np_dtype):
        """Assemble a P('parts')-sharded array from per-part builders
        run ONLY for this process's partitions."""
        shards = [np.ascontiguousarray(
            build(p)[None].astype(np_dtype, copy=False)) for p in local]
        return make_sharded_array(mesh, local, shards, (P,) + shape)

    def node_field(get, fill, np_dtype, extra=()):
        def build(p):
            l, r = pg.bounds[p]
            out = np.full((pn,) + extra, fill, dtype=np_dtype)
            if r >= l:
                out[:r - l + 1] = get(l, r + 1)
            return out
        return build

    if halo == "ring":
        # Fully partition-local ring prep: pair lists from this host's
        # own column reads; the uniform pair width (an SPMD shape, so
        # every host must agree) comes from an O(P) max/sum collective
        # over per-part stats — never a whole-graph pass.
        from .ring import (build_ring_pairs, pack_ring_part,
                           round_pair_edges)
        pairs = {p: build_ring_pairs(
            pg, p, partition_col(pg, src.col_slice, p)) for p in local}
        stats = {p: (max((d.shape[0] for _, d in pairs[p].values()),
                         default=1),
                     sum(d.shape[0] for _, d in pairs[p].values()))
                 for p in local}
        max_pair, total_real = _allreduce_part_stats(mesh, local, stats)
        pair_edges = round_pair_edges(max_pair)
        # pack once per part — each pack allocates two [P, pair_edges]
        # tables (hundreds of MB at Amazon-2M scale)
        packed = {p: pack_ring_part(pairs[p], P, pair_edges, pn)
                  for p in local}
        ring_src = put_parts(lambda p: packed[p][0], (P, pair_edges),
                             np.int32)
        ring_dst = put_parts(lambda p: packed[p][1], (P, pair_edges),
                             np.int32)
        stub = lambda p: np.zeros(1, np.int32)
        return ShardedData(
            feats=put_parts(node_field(src.features, 0, np.float32,
                                       (src.in_dim,)),
                            (pn, src.in_dim), np.dtype(dtype)),
            labels=put_parts(node_field(src.labels, 0, np.int32), (pn,),
                             np.int32),
            mask=put_parts(node_field(src.mask, MASK_NONE, np.int32),
                           (pn,), np.int32),
            edge_src=put_parts(stub, (1,), np.int32),
            edge_dst=put_parts(stub, (1,), np.int32),
            in_degree=put_parts(lambda p: pg.part_in_degree[p], (pn,),
                                np.int32),
            ell_row_pos=put_parts(stub, (1,), np.int32),
            ring_idx=(ring_src, ring_dst),
            ring_padding_ratio=(P * P * pair_edges) / max(total_real, 1),
        )

    # local parts' padded columns, remapped once and reused by both the
    # edge_src field and the ELL table build
    cols = {p: remap_col_to_padded(pg, partition_col(pg, src.col_slice, p))
            for p in local}
    use_stub = aggr_impl in ("ell", "pallas", "sectioned", "attn_flat8",
                             "flat_sum", "bdense")

    def edge_src_build(p):
        return cols[p]

    def edge_dst_build(p):
        return np.repeat(np.arange(pn, dtype=np.int32),
                         np.diff(pg.part_row_ptr[p]))

    ell_idx = ()
    ell_row_id = ()
    ell_row_pos = put_parts(lambda p: np.zeros(1, np.int32), (1,),
                            np.int32)
    ring_idx = ()
    if aggr_impl in ("ell", "pallas"):
        # plan from part_row_ptr — the SAME degrees part_tables' bucket
        # build sees (padding edges can inflate the last real row's
        # degree when real_nodes[p] == part_nodes; see ell_shape_plan)
        widths, rows_per_width = ell_shape_plan(pg.part_row_ptr,
                                                pg.real_nodes)
        dummy = P * pn

        def part_tables(p):
            n = int(pg.real_nodes[p])
            ptr = pg.part_row_ptr[p, :n + 1].astype(np.int64)
            buckets = build_ell(ptr, edge_src_build(p))
            return place_ell_part(buckets, widths, rows_per_width, pn,
                                  dummy)

        tables = {p: part_tables(p) for p in local}
        ell_idx = tuple(
            put_parts(lambda p, wi=wi: tables[p][0][wi],
                      (rows_per_width[w], w), np.int32)
            for wi, w in enumerate(widths))
        ell_row_pos = put_parts(lambda p: tables[p][1], (pn,), np.int32)
        ell_row_id = tuple(
            put_parts(lambda p, wi=wi: tables[p][2][wi],
                      (rows_per_width[w],), np.int32)
            for wi, w in enumerate(widths))

    sect_idx = ()
    sect_sub_dst = ()
    sect_meta = ()
    if aggr_impl in ("attn_flat8", "flat_sum"):
        # the uniform flat layout (attention's attn_flat8 and the sum
        # path's flat_sum share it), partition-local: ONE section
        # spanning all gathered sources (same layout shard_dataset
        # builds; DistributedTrainer routes these to the flat8 gctx
        # fields), chunk plan agreed via the O(P) collective.  No
        # baked fused weights multihost (shard_dataset_local has no
        # fuse path for any impl) — the builder's generic d-scaling
        # fallback covers fused configs when flat8_w is None
        from ..core.ell import (clean_part_ptr, section_sub_counts,
                                sectioned_from_graph, sectioned_plan)
        src_rows = P * pn
        ptrs = {p: clean_part_ptr(pg.part_row_ptr[p], pg.real_nodes[p],
                                  pn) for p in local}
        cnts = {p: section_sub_counts(
            ptrs[p], cols[p][:int(ptrs[p][-1])], pn, src_rows,
            src_rows) for p in local}
        counts_max = _allreduce_part_vec_max(mesh, local, cnts)
        seg, plan = sectioned_plan(counts_max, seg_rows=8192)
        sects = {p: sectioned_from_graph(
            ptrs[p], cols[p][:int(ptrs[p][-1])], pn, src_rows=src_rows,
            section_rows=src_rows, seg_rows=seg, chunks_plan=plan,
            counts=cnts[p]) for p in local}
        sect_idx = (put_parts(lambda p: sects[p].idx[0],
                              (plan[0], seg, 8), np.int32),)
        sect_sub_dst = (put_parts(lambda p: sects[p].sub_dst[0],
                                  (plan[0], seg), np.int32),)
    def local_sectioned_tables(ptrs, colmap):
        """Stacked sectioned tables from per-part (ptr, cols) dicts —
        the ONE multihost implementation of the uniform-chunk-plan
        agreement (O(P * n_sec) elementwise-max collective over
        per-part sub-row counts, same pattern as the ring's pair
        width; never a whole-graph pass).  Shared by the 'sectioned'
        branch and the bdense residual, mirroring
        distributed._sectioned_tables."""
        from ..core.ell import (default_section_rows,
                                section_sub_counts, sectioned_from_graph,
                                sectioned_plan)
        sec_rows = (section_rows if section_rows is not None
                    else default_section_rows(sect_u16))
        idx_np_dtype = np.uint16 if sect_u16 else np.int32
        src_rows = P * pn
        cnts = {p: section_sub_counts(
            ptrs[p], colmap[p], pn, src_rows,
            sec_rows, sub_w=sect_sub_w) for p in local}
        counts_max = _allreduce_part_vec_max(mesh, local, cnts)
        seg, plan = sectioned_plan(counts_max)
        sects = {p: sectioned_from_graph(
            ptrs[p], colmap[p], pn, src_rows=src_rows,
            section_rows=sec_rows, seg_rows=seg, chunks_plan=plan,
            counts=cnts[p], sub_w=sect_sub_w) for p in local}
        if sect_u16:
            sects = {p: s.with_idx_dtype(np.uint16)
                     for p, s in sects.items()}
        first = sects[local[0]]
        return (
            tuple(put_parts(lambda p, s=s: sects[p].idx[s],
                            (plan[s], seg, sect_sub_w), idx_np_dtype)
                  for s in range(len(first.idx))),
            tuple(put_parts(lambda p, s=s: sects[p].sub_dst[s],
                            (plan[s], seg), np.int32)
                  for s in range(len(first.sub_dst))),
            tuple(zip(first.sec_starts, first.sec_sizes)))

    if aggr_impl == "sectioned":
        from ..core.ell import clean_part_ptr
        ptrs = {p: clean_part_ptr(pg.part_row_ptr[p], pg.real_nodes[p],
                                  pn) for p in local}
        sect_idx, sect_sub_dst, sect_meta = local_sectioned_tables(
            ptrs, {p: cols[p][:int(ptrs[p][-1])] for p in local})

    bd_tabs = ()
    bd_vpad = 0
    bd_src_vpad = 0
    bd_occupancy = ()
    if aggr_impl == "bdense":
        # partition-local block-dense plans over the rectangular tile
        # space (local dst rows x gathered sources), exactly
        # distributed.shard_dataset's layout.  The two SPMD shapes
        # every host must agree on — the uniform per-part block count
        # and the residual sectioned chunk plan — come from the same
        # O(P) collectives the sectioned/ring branches use; no
        # whole-graph pass.
        from ..core.ell import clean_part_ptr
        from ..ops.blockdense import (BLOCK, U4_MAX, pack_a_u4,
                                      plan_blocks)
        src_rows = P * pn
        ptrs = {p: clean_part_ptr(pg.part_row_ptr[p], pg.real_nodes[p],
                                  pn) for p in local}

        def _mk(budget):
            # group>1 plans arrive per-part group-aligned BEFORE the
            # nblk_max collective: every host's count is a group
            # multiple, so the uniform stacked tail below pads in
            # whole dummy-dst groups
            return {p: plan_blocks(
                ptrs[p], cols[p][:int(ptrs[p][-1])], pn,
                min_fill=bdense_min_fill, a_budget_bytes=budget,
                num_cols=src_rows, group=bdense_group) for p in local}

        # the 2x-budget-then-pack policy (plan_blocks_packed), decided
        # GLOBALLY: one more O(P) collective agrees the max slot
        # multiplicity, so every host packs (or not) identically and
        # the SPMD table keeps one trailing width.  Branches below
        # depend only on globally-reduced values — every host runs
        # the SAME collective sequence.
        plans = _mk(bdense_a_budget * 2
                    if bdense_a_budget is not None else None)
        nblk_max, _ = _allreduce_part_stats(
            mesh, local, {p: (plans[p].n_blocks, 0) for p in local})
        max_mult, _ = _allreduce_part_stats(
            mesh, local,
            {p: (int(plans[p].a_blocks.max())
                 if plans[p].n_blocks else 0, 0) for p in local})
        packable = max_mult <= U4_MAX
        if packable:
            # pack_a_u4 packs EMPTY parts too — a zero-block part on
            # one host must still stack at the uniform u4 width
            plans = {p: pack_a_u4(plans[p]) for p in local}
        elif bdense_a_budget is not None and \
                nblk_max * BLOCK * BLOCK > bdense_a_budget:
            # some part over the true cap and packing can't save it:
            # re-plan at 1x and re-agree the uniform block count
            plans = _mk(bdense_a_budget)
            nblk_max, _ = _allreduce_part_stats(
                mesh, local,
                {p: (plans[p].n_blocks, 0) for p in local})
        bd_occupancy = tuple(plans[p].occupancy() for p in local)
        if nblk_max:
            bd_vpad = plans[local[0]].vpad
            bd_src_vpad = plans[local[0]].src_vpad
            n_dst_tiles = bd_vpad // BLOCK
            a_w = BLOCK // 2 if packable else BLOCK

            def bd_field(get, fill, np_dtype, extra=()):
                def build(p):
                    pl = plans[p]
                    out = np.full((nblk_max,) + extra, fill,
                                  dtype=np_dtype)
                    out[:pl.n_blocks] = get(pl)
                    return out
                return build
            # padding blocks: zero A scattered into the dummy output
            # tile — numerically inert, same scheme as shard_dataset
            bd_tabs = (
                put_parts(bd_field(lambda pl: pl.a_blocks, 0, np.uint8,
                                   (BLOCK, a_w)),
                          (nblk_max, BLOCK, a_w), np.uint8),
                put_parts(bd_field(lambda pl: pl.src_blk, 0, np.int32),
                          (nblk_max,), np.int32),
                put_parts(bd_field(lambda pl: pl.dst_blk, n_dst_tiles,
                                   np.int32),
                          (nblk_max,), np.int32))
        # residual scattered edges -> the stacked sectioned tables
        # (every edge, when no tile qualifies anywhere)
        sect_idx, sect_sub_dst, sect_meta = local_sectioned_tables(
            {p: plans[p].res_row_ptr for p in local},
            {p: plans[p].res_col for p in local})

    stub_build = lambda p: np.zeros(1, np.int32)
    return ShardedData(
        feats=put_parts(node_field(src.features, 0, np.float32,
                                   (src.in_dim,)),
                        (pn, src.in_dim), np.dtype(dtype)),
        labels=put_parts(node_field(src.labels, 0, np.int32), (pn,),
                         np.int32),
        mask=put_parts(node_field(src.mask, MASK_NONE, np.int32), (pn,),
                       np.int32),
        edge_src=put_parts(stub_build if use_stub else edge_src_build,
                           (1,) if use_stub else (pe,), np.int32),
        edge_dst=put_parts(stub_build if use_stub else edge_dst_build,
                           (1,) if use_stub else (pe,), np.int32),
        in_degree=put_parts(lambda p: pg.part_in_degree[p], (pn,),
                            np.int32),
        ell_idx=ell_idx,
        ell_row_pos=ell_row_pos,
        ell_row_id=ell_row_id,
        ring_idx=ring_idx,
        sect_idx=sect_idx,
        sect_sub_dst=sect_sub_dst,
        sect_meta=sect_meta,
        bd_tabs=bd_tabs,
        bd_vpad=bd_vpad,
        bd_src_vpad=bd_src_vpad,
        bd_occupancy=bd_occupancy,
        bd_group=bdense_group if bd_tabs else 1,
    )
