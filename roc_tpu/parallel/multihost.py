"""Multi-host (DCN x ICI) distributed runtime.

The reference runs multi-machine through Legion address spaces over
GASNet (``Makefile:26``) with NCCL linked for collectives
(``nccl_task.cu:19-38``; the multi-rank init is dead-coded,
``gnn.cc:630-642``) and a mapper that round-robins partitions across
machines first (``gnn_mapper.cc:120-131``).  The TPU-native
equivalents here:

- :func:`init_distributed` — ``jax.distributed.initialize`` wrapper
  (the NCCL-communicator/GASNet bootstrap analog); env-driven so the
  same entry point works under any launcher.
- :func:`make_parts_mesh` — a 1-D ``'parts'`` mesh laid out so that
  consecutive partitions land on the same host: the ring/all-gather
  halo then crosses DCN only ``num_hosts`` times per rotation instead
  of every hop (the mapper's machine-first round-robin solved the
  inverse problem — here locality, not spread, minimizes the slow
  link).
- :func:`process_local_parts` / :func:`make_sharded_array` — each host
  materializes only its own partitions' rows and the global jax.Array
  is assembled from per-process local shards
  (``jax.make_array_from_single_device_arrays``) — the analog of the
  reference's per-partition loader tasks running on each node's CPUs
  (``load_task.cu:201-269``) rather than one host broadcasting.

Single-process (including the 8-virtual-device CPU test rig) is the
degenerate case throughout; nothing here requires real multi-host
hardware to compile or test.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None
                     ) -> None:
    """Initialize the JAX distributed runtime (multi-host DCN).

    Arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``), so launchers only need to export those.  A
    no-op when single-process (no coordinator configured) — the
    single-host paths then work unchanged.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)


def make_parts_mesh(num_parts: Optional[int] = None,
                    devices: Optional[List] = None) -> Mesh:
    """1-D ``'parts'`` mesh across all processes' devices — alias of
    :func:`roc_tpu.parallel.distributed.make_mesh` (one constructor,
    one partition->device layout; see its docstring for the DCN
    locality invariant)."""
    from .distributed import make_mesh
    return make_mesh(num_parts, devices)


def process_local_parts(mesh: Mesh) -> List[int]:
    """Partition indices whose device lives on this process — the set
    of shards this host must load (the reference's per-node loader
    tasks, ``load_task.cu:201-269``, selected by the mapper; here
    selected by mesh placement)."""
    pid = jax.process_index()
    return [i for i, d in enumerate(mesh.devices.reshape(-1))
            if d.process_index == pid]


def make_sharded_array(mesh: Mesh, local_parts: List[int],
                       local_shards: Sequence[np.ndarray],
                       global_shape: Tuple[int, ...]) -> jax.Array:
    """Assemble a ``P('parts')``-sharded global array from this
    process's shard data only (no cross-host broadcast).

    local_shards[i] is the [1, ...] slice for partition
    ``local_parts[i]``.  On a single process this reduces to a plain
    ``device_put`` of the stacked array.
    """
    sharding = NamedSharding(mesh, P("parts"))
    devices = mesh.devices.reshape(-1)
    singles = [
        jax.device_put(np.ascontiguousarray(shard), devices[part])
        for part, shard in zip(local_parts, local_shards)
    ]
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, singles)


def shard_dataset_local(dataset, pg, mesh: Mesh, dtype=None,
                        aggr_impl: str = "segment",
                        halo: str = "gather"):
    """Multi-host version of ``distributed.shard_dataset``: identical
    host-side preprocessing, but each process uploads only its own
    partitions' shards (no cross-host broadcast).  Returns the same
    ``ShardedData`` so ``DistributedTrainer`` works unchanged.

    (The host-side preprocessing is currently done for all partitions
    on every host — those arrays are cheap relative to feature data;
    the upload, which dominates, is local-only.)
    """
    import jax.numpy as jnp
    from .distributed import shard_dataset

    if dtype is None:
        dtype = jnp.float32
    local = process_local_parts(mesh)

    def put(arr):
        return make_sharded_array(
            mesh, local, [arr[p:p + 1] for p in local], arr.shape)

    return shard_dataset(dataset, pg, mesh, dtype=dtype,
                         aggr_impl=aggr_impl, halo=halo, put=put)
